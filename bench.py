"""Benchmarks: the BASELINE.md pinned configs on one TPU chip.

Three hand-built device pipelines (the presto-benchmark suite pattern —
hand-assembled operator pipelines, AbstractOperatorBenchmark.java:97,
HandTpchQuery1.java / HandTpchQuery6.java / HashBuildAndJoinBenchmark):

1. TPC-H SF1 Q1  — scan + grouped aggregation (headline metric)
2. TPC-H SF10 Q6 — predicate + projection + global aggregation
3. TPC-H SF1 Q3 core — 3-way join + aggregation + TopN, exploiting
   TPC-H's dense integer keys TPU-first: FK joins become boolean-table
   gathers, the revenue aggregation is a scatter-add over the dense
   orderkey domain, TopN is lax.top_k — no sorts, so the program is both
   compile-cheap and HBM-bound (the reference's HashBuilder/LookupJoin
   for the same query walks hash tables row-at-a-time).

Each config reports rows/s and effective input bytes/s, with parity
against a vectorized-numpy CPU implementation (the stand-in for the
reference's CPU operator pipeline — its codegen also reduces to tight
CPU loops over columnar arrays; the reference publishes no absolute
numbers, BASELINE.md).

Config 6 (``bench_engine_q1q6``) measures the SHIPPED engine: TPC-H Q1 +
Q6 SQL through LocalQueryRunner (planner + operator tier + pipeline
fusion), reported in ``extras`` next to the hand-kernel configs so the
artifact tracks what the engine executes, not just what hand-built
kernels can reach (ROADMAP #10).

Config 7 (``bench_mesh_q1q6``) pushes the same two queries through the
DISTRIBUTED tier — a real 2-worker DistributedQueryRunner cluster
(coordinator + workers on ephemeral HTTP ports, serde'd pages on the
exchange wire, partial/final aggregation split across fragments) — the
engine-path depth ROADMAP #10 still wanted.  ``vs_baseline`` is the
single-process engine wall ratio, so the line prices the distribution
overhead directly.

Config 10 (``bench_concurrent_qps``) measures the SERVING tier: N
concurrent clients (tools/qps_run.py closed loop) against a live
2-worker cluster with resource-group admission engaged — QPS and
p50/p95/p99 latency at 4 concurrency levels, per-client exact-rows
parity, plan-cache hit rate, and jit_compiles == 0 on the second
execution of a cached plan (the dispatcher + plan-cache PR).

Timing methodology (axon tunnel quirks): run K dependence-chained
iterations INSIDE one jitted fori_loop and take the slope between two K
values, so RPC overhead and sync-polling granularity cancel.

Prints exactly ONE JSON line; the headline is Q1 and the other configs
ride in "extras":
    {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N,
     "extras": [...]}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

Q6_DATE_LO, Q6_DATE_HI = 8766, 9131          # 1994-01-01 .. 1995-01-01
# BETWEEN 0.05 AND 0.07 via class midpoints: the generated discounts are
# the 11 cent classes 0.00..0.10, and the 0.05/0.07 boundaries sit on
# float knife-edges that f32-physical device doubles and host f64 round
# differently; midpoint thresholds select exactly {0.05,0.06,0.07} under
# either precision
Q6_DISC_LO, Q6_DISC_HI = 0.045, 0.075
Q3_DATE = 9204                               # 1995-03-15, epoch days


def _slope_time(make_chained, args) -> float:
    """Seconds per iteration via the two-K dependence-chained slope."""
    f5 = make_chained(5)
    np.asarray(f5(args))
    t0 = time.perf_counter()
    np.asarray(f5(args))
    rough = max((time.perf_counter() - t0) / 5, 1e-5)
    k1 = 3
    k2 = k1 + max(20, min(2000, int(4.0 / rough)))
    ts = []
    for k in (k1, k2):
        f = make_chained(k)
        np.asarray(f(args))  # compile + warm (sync via host read)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(f(args))
            best = min(best, time.perf_counter() - t0)
        ts.append(best)
    return max((ts[1] - ts[0]) / (k2 - k1), 1e-9)


def _col_bytes(arrays) -> int:
    return int(sum(np.asarray(a).nbytes if not hasattr(a, "nbytes")
                   else a.nbytes for a in arrays))


# ---------------------------------------------------------------------------
# Config 1: TPC-H Q1 (scan + grouped aggregation)
# ---------------------------------------------------------------------------

def _cpu_q1(rf, ls, qty, price, disc, tax, shipdate, n):
    sel = shipdate[:n] <= 10471
    rf, ls = rf[:n][sel], ls[:n][sel]
    qty, price = qty[:n][sel], price[:n][sel]
    disc, tax = disc[:n][sel], tax[:n][sel]
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)
    key = rf.astype(np.int64) * 64 + ls
    uniq, inv = np.unique(key, return_inverse=True)
    out = []
    for col in (qty, price, disc_price, charge, disc):
        out.append(np.bincount(inv, weights=col, minlength=len(uniq)))
    out.append(np.bincount(inv, minlength=len(uniq)))
    return uniq, out


def bench_q1(scale: float):
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _q1_arrays, q1_step

    args = _q1_arrays(scale)

    def chained(k):
        def body(_, carry):
            a, acc = carry
            out = q1_step(*a[:2], a[2] + (acc - acc).astype(a[2].dtype),
                          *a[3:])
            return (a, acc + out[3][0])
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    device_s = _slope_time(chained, args)
    n = int(args[-1])

    out = jax.jit(q1_step)(*args)
    host = [np.asarray(a) for a in args[:-1]]
    t0 = time.perf_counter()
    cpu = _cpu_q1(*host, n)
    cpu_s = time.perf_counter() - t0

    ng = int(out[2])
    dev_key = (np.asarray(out[0])[:ng].astype(np.int64) * 64
               + np.asarray(out[1])[:ng])
    order = np.argsort(dev_key)
    ok = bool(np.array_equal(dev_key[order], cpu[0]))
    for i, want in enumerate(cpu[1]):
        got = np.asarray(out[3 + i])[:ng][order]
        ok = ok and bool(np.allclose(got, want, rtol=1e-6))
    nbytes = _col_bytes(host) * n // max(host[0].shape[0], 1)
    return {
        "metric": f"tpch_sf{scale:g}_q1_rows_per_sec_per_chip",
        "value": round(n / device_s, 1), "unit": "rows/s",
        "vs_baseline": round(n / device_s / (n / cpu_s), 3),
        "bytes_per_sec": round(nbytes / device_s, 1),
        "parity": ok,
    }


# ---------------------------------------------------------------------------
# Config 2: TPC-H Q6 (filter + projection + global sum)
# ---------------------------------------------------------------------------

def _q6_arrays(scale: float):
    import jax.numpy as jnp

    from presto_tpu.batch import concat_batches, next_bucket
    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=scale)
    handle = conn.get_table("lineitem")
    cols = ["l_shipdate", "l_discount", "l_quantity", "l_extendedprice"]
    batches = []
    for split in conn.get_splits(handle, 1):
        batches.extend(conn.page_source(split, cols, 1 << 24))
    b = concat_batches(batches) if len(batches) > 1 else batches[0]
    cap = next_bucket(b.num_rows)
    b = b.pad_rows(cap)
    arrays = tuple(jnp.asarray(c.values) for c in b.columns)
    return arrays + (jnp.asarray(b.num_rows, jnp.int64),)


def q6_step(shipdate, disc, qty, price, num_rows):
    """WHERE l_shipdate in [1994, 1995) AND l_discount BETWEEN 0.05 AND
    0.07 AND l_quantity < 24 -> SUM(l_extendedprice * l_discount), fused
    into the aggregation as a live mask (HandTpchQuery6 role)."""
    import jax.numpy as jnp

    live = jnp.arange(shipdate.shape[0]) < num_rows
    sel = (live & (shipdate >= Q6_DATE_LO) & (shipdate < Q6_DATE_HI)
           & (disc > Q6_DISC_LO) & (disc < Q6_DISC_HI) & (qty < 24.0))
    return jnp.where(sel, price * disc, 0.0).sum()


def _cpu_q6(shipdate, disc, qty, price, n):
    sel = ((shipdate[:n] >= Q6_DATE_LO) & (shipdate[:n] < Q6_DATE_HI)
           & (disc[:n] > Q6_DISC_LO) & (disc[:n] < Q6_DISC_HI)
           & (qty[:n] < 24.0))
    return float((price[:n][sel] * disc[:n][sel]).sum())


def bench_q6(scale: float):
    import jax
    import jax.numpy as jnp

    args = _q6_arrays(scale)

    def chained(k):
        def body(_, carry):
            a, acc = carry
            s = q6_step(a[0] + (acc - acc).astype(a[0].dtype), *a[1:])
            return (a, acc + s)
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    device_s = _slope_time(chained, args)
    n = int(args[-1])
    host = [np.asarray(a) for a in args[:-1]]
    t0 = time.perf_counter()
    want = _cpu_q6(*host, n)
    cpu_s = time.perf_counter() - t0
    got = float(jax.jit(q6_step)(*args))
    ok = bool(np.isclose(got, want, rtol=1e-6))
    nbytes = _col_bytes(host) * n // max(host[0].shape[0], 1)
    return {
        "metric": f"tpch_sf{scale:g}_q6_rows_per_sec_per_chip",
        "value": round(n / device_s, 1), "unit": "rows/s",
        "vs_baseline": round(n / device_s / (n / cpu_s), 3),
        "bytes_per_sec": round(nbytes / device_s, 1),
        "parity": ok,
    }


# ---------------------------------------------------------------------------
# Config 3: TPC-H Q3 core (3-way join + aggregation + TopN)
# ---------------------------------------------------------------------------

def _q3_arrays(scale: float):
    import jax.numpy as jnp

    from presto_tpu.batch import concat_batches, next_bucket
    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=scale)

    def load(table, cols):
        h = conn.get_table(table)
        batches = []
        for split in conn.get_splits(h, 1):
            batches.extend(conn.page_source(split, cols, 1 << 24))
        return concat_batches(batches) if len(batches) > 1 else batches[0]

    cust = load("customer", ["c_custkey", "c_mktsegment"])
    seg = cust.columns[1]
    building_code = seg.dictionary.code_of("BUILDING")
    n_cust = cust.num_rows
    # dense boolean membership table over the custkey domain (keys are
    # 1..N in order): the build side of join #1, as one gather table
    cust_building = np.zeros(n_cust + 1, bool)
    cust_building[np.asarray(cust.columns[0].values)[:n_cust]] = (
        np.asarray(seg.values)[:n_cust] == building_code)

    orders = load("orders", ["o_orderkey", "o_custkey", "o_orderdate"])
    n_ord = orders.num_rows
    ocust = np.asarray(orders.columns[1].values)[:n_ord]
    odate = np.asarray(orders.columns[2].values)[:n_ord]

    li = load("lineitem", ["l_orderkey", "l_extendedprice", "l_discount",
                           "l_shipdate"])
    n_li = li.num_rows
    cap = next_bucket(n_li)
    li = li.pad_rows(cap)
    # f32/i32 on device: the v5e stores "f64" as f32 anyway (X64 rewrite)
    # and emulated 64-bit elementwise ops would dominate the runtime;
    # per-order revenue sums at most 7 f32 terms so precision holds
    okey0 = np.clip(np.asarray(li.columns[0].values) - 1, 0,
                    n_ord - 1).astype(np.int32)
    arrays = (
        jnp.asarray(cust_building),
        jnp.asarray(ocust.astype(np.int32)),
        jnp.asarray(odate.astype(np.int32)),
        jnp.asarray(okey0),
        jnp.asarray(np.asarray(li.columns[1].values,
                               dtype=np.float32)),
        jnp.asarray(np.asarray(li.columns[2].values,
                               dtype=np.float32)),
        jnp.asarray(np.asarray(li.columns[3].values, dtype=np.int32)),
        jnp.asarray(n_li, jnp.int64),
    )
    rows = n_cust + n_ord + n_li
    # 4 lineitem device arrays (okey0/price/disc/ship, 4B each)
    nbytes = (cust_building.nbytes + 2 * 4 * n_ord + 4 * 4 * n_li)
    # keep f64 copies for the CPU oracle
    host = (cust_building, ocust, odate,
            np.asarray(li.columns[0].values)[:n_li],
            np.asarray(li.columns[1].values)[:n_li],
            np.asarray(li.columns[2].values)[:n_li],
            np.asarray(li.columns[3].values)[:n_li], n_li)
    return arrays, host, rows, nbytes


def q3_step(cust_building, ocust, odate, okey0, price, disc, ship, n_li):
    """Q3's join+agg+TopN core as one XLA program over dense keys:

        sel_orders = building[o_custkey] & o_orderdate < DATE   (join #1
                     + filter: a gather and a compare)
        sel_line   = sel_orders[l_orderkey] & l_shipdate > DATE (join #2)
        revenue    = 7-tap same-key windowed sum at each order's last
                     lineitem (orders have <= 7 adjacent lineitems, so
                     no scatter and no sort)
        top 10 revenue via blocked two-stage lax.top_k

    The reference executes this as HashBuilder/LookupJoin x2 +
    HashAggregation + TopN (presto-main/.../operator/, SURVEY §3.4);
    dense TPC-H keys let the TPU do it bandwidth-bound with no hash
    table."""
    import jax
    import jax.numpy as jnp

    sel_ord = cust_building[ocust] & (odate < Q3_DATE)
    live = jnp.arange(okey0.shape[0]) < n_li
    sel_li = live & (ship > Q3_DATE) & sel_ord[okey0]
    contrib = jnp.where(sel_li, price * (1.0 - disc), jnp.float32(0))
    rev = contrib
    for j in range(1, 7):
        shifted = jnp.concatenate(
            [jnp.zeros(j, contrib.dtype), contrib[:-j]])
        same = jnp.concatenate(
            [jnp.zeros(j, bool), okey0[j:] == okey0[:-j]])
        rev = rev + jnp.where(same, shifted, 0)
    end = jnp.concatenate([okey0[1:] != okey0[:-1], jnp.ones(1, bool)])
    rev = jnp.where(end & live, rev, jnp.float32(-1.0))
    B = 1024
    pad = (-rev.shape[0]) % B
    r2 = jnp.pad(rev, (0, pad), constant_values=-1.0).reshape(B, -1)
    tv, ti = jax.lax.top_k(r2, 10)
    base = (jnp.arange(B) * r2.shape[1])[:, None]
    cv, ci = jax.lax.top_k(tv.reshape(-1), 10)
    pos = (base + ti).reshape(-1)[ci]
    return cv, okey0[jnp.clip(pos, 0, okey0.shape[0] - 1)] + 1


def _cpu_q3(cust_building, ocust, odate, l_okey, l_price, l_disc,
            l_ship, n_li):
    sel_ord = cust_building[ocust] & (odate < Q3_DATE)
    okey0 = l_okey[:n_li] - 1
    sel_li = (l_ship[:n_li] > Q3_DATE) & sel_ord[okey0]
    contrib = np.where(sel_li, l_price[:n_li] * (1.0 - l_disc[:n_li]), 0.0)
    rev = np.bincount(okey0, weights=contrib, minlength=len(ocust))
    top = np.argsort(-rev, kind="stable")[:10]
    return rev[top]


def bench_q3(scale: float):
    import jax
    import jax.numpy as jnp

    args, host, rows, nbytes = _q3_arrays(scale)

    def chained(k):
        def body(_, carry):
            a, acc = carry
            out = q3_step(a[0], a[1], a[2],
                          a[3] + (acc - acc).astype(a[3].dtype), *a[4:])
            return (a, acc + out[0][0].astype(jnp.float64))
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    device_s = _slope_time(chained, args)

    t0 = time.perf_counter()
    want = _cpu_q3(*host)
    cpu_s = time.perf_counter() - t0
    got = np.sort(np.asarray(jax.jit(q3_step)(*args)[0]))[::-1]
    # f32 revenue sums: ~1e-5 relative (SQL float aggregation order is
    # unspecified; the reference reorders too)
    ok = bool(np.allclose(got, np.sort(want)[::-1], rtol=1e-4))
    return {
        "metric": f"tpch_sf{scale:g}_q3_join_agg_rows_per_sec_per_chip",
        "value": round(rows / device_s, 1), "unit": "rows/s",
        "vs_baseline": round(rows / device_s / (rows / cpu_s), 3),
        "bytes_per_sec": round(nbytes / device_s, 1),
        "parity": ok,
    }


def bench_whole_query_q3(scale: float):
    """The generic one-XLA-program tier (parallel/sqlmesh) on TPC-H Q3
    text — the flagship mode's warm wall clock (cold compile amortized
    by the persistent XLA cache)."""
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.parallel.sqlmesh import MeshQueryRunner

    sql = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""
    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=scale))
    r = MeshQueryRunner(reg, "tpch", n_devices=1)
    r.execute(sql)                         # compile + warm
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = r.execute(sql)
        walls.append(time.perf_counter() - t0)
    return {
        "metric": f"tpch_sf{scale:g}_q3_whole_query_warm_wall_s",
        "value": round(min(walls), 3), "unit": "s",
        "vs_baseline": 0.0,
        "note": ("generic SPMD lowering, one program; includes the "
                 "remote-TPU tunnel's per-dispatch latency"),
        "rows": len(res.rows),
    }


# ---------------------------------------------------------------------------
# Config 4: TPC-H Q9 (5-way join + grouped aggregation over (nation, year))
# ---------------------------------------------------------------------------

def _epoch_days_to_year(days: np.ndarray) -> np.ndarray:
    return (days.astype("datetime64[D]").astype("datetime64[Y]")
            .astype(np.int64) + 1970).astype(np.int32)


def _q9_tables(scale: float):
    """Build-side lookup tables for Q9, laid out for dense device gathers:
    part's LIKE-'%green%' mask over the partkey domain, partsupp as four
    slot-rows per part (row (pk-1)*4+i — the generator emits them
    adjacent), supplier nation over the suppkey domain, and order year
    over the dense orderkey domain.  The reference runs this as a 6-way
    HashBuilder/LookupJoin tree (BenchmarkSuite.java:33 configs); dense
    TPC-H keys let the TPU resolve every join with one gather each."""
    from presto_tpu.connectors.tpch import COLORS, _S_PART, TpchConnector, u_int

    conn = TpchConnector(scale=scale).generator
    P, S, O = conn.n_part, conn.n_supplier, conn.n_orders
    keys = np.arange(1, P + 1, dtype=np.int64)
    gi = COLORS.index("green")
    gm = np.zeros(P, bool)
    for i in range(5):  # p_name is five color words; 'green' is exact
        gm |= u_int(_S_PART + 10 + i, keys, 0, len(COLORS) - 1) == gi
    green = np.zeros(P + 1, bool)
    green[1:] = gm

    ps = conn.gen_partsupp(["ps_suppkey", "ps_supplycost"], 1, P + 1)
    ps_sk = np.asarray(ps.columns[0].values).astype(np.int32)
    ps_cost = np.asarray(ps.columns[1].values).astype(np.float32)

    sup = conn.gen_supplier(["s_nationkey"], 1, S + 1)
    s_nat = np.zeros(S + 1, np.int32)
    s_nat[1:] = np.asarray(sup.columns[0].values)

    odate = conn._order_date(np.arange(1, O + 1, dtype=np.int64))
    o_year = (_epoch_days_to_year(odate) - 1992).astype(np.int32)  # 0..6
    return conn, green, ps_sk, ps_cost, s_nat, o_year


def q9_step(green, ps_sk, ps_cost, s_nat, o_year,
            pk, sk, okey0, qty, price, disc, n_rows):
    """Q9's join+agg stage as one XLA program: four dense-key gathers
    (part mask, partsupp 4-slot compare, supplier nation, order year)
    feed a 175-group scatter-add over (nation, year).  Role:
    presto-benchmark's hand-built pipelines (HandTpchQuery1.java:97
    pattern) over the 6-way join of BenchmarkSuite.java:33."""
    import jax.numpy as jnp

    live = jnp.arange(pk.shape[0]) < n_rows
    sel = live & green[pk]
    cand = ((pk - 1) * 4)[:, None] + jnp.arange(4, dtype=jnp.int32)[None, :]
    cand = jnp.clip(cand, 0, ps_sk.shape[0] - 1)
    hit = ps_sk[cand] == sk[:, None]
    cost = (ps_cost[cand] * hit).sum(axis=1)
    amount = price * (1.0 - disc) - cost * qty
    g = s_nat[sk] * 7 + o_year[okey0]
    sums = (jnp.zeros(176, jnp.float32)
            .at[jnp.where(sel, g, 175)]
            .add(jnp.where(sel, amount, jnp.float32(0))))
    return sums[:175]


def _cpu_q9(green, ps_sk, ps_cost, s_nat, o_year, chunks):
    out = np.zeros(175)
    for pk, sk, okey0, qty, price, disc, n in chunks:
        pk, sk = pk[:n], sk[:n]
        okey0, qty = okey0[:n], qty[:n]
        price, disc = price[:n].astype(np.float64), disc[:n].astype(np.float64)
        sel = green[pk]
        cost = np.zeros(n)
        for i in range(4):
            m = ps_sk[(pk - 1) * 4 + i] == sk
            cost = np.where(m, ps_cost[(pk - 1) * 4 + i].astype(np.float64),
                            cost)
        amount = price * (1.0 - disc) - cost * qty
        g = s_nat[sk] * 7 + o_year[okey0]
        out += np.bincount(g[sel], weights=amount[sel], minlength=175)
    return out


def _gen_lineitem_chunks(conn, cols, np_dtypes, chunk_orders):
    """Generate lineitem host arrays chunked on ORDER boundaries (each
    order's lineitems stay within one chunk), padded to one shared
    capacity so every chunk reuses the same compiled program."""
    from presto_tpu.batch import next_bucket

    O = conn.n_orders
    chunk_orders = min(chunk_orders, O)
    cap = next_bucket(int(chunk_orders * 4.3) + 16)
    chunks = []
    for lo in range(1, O + 1, chunk_orders):
        hi = min(lo + chunk_orders, O + 1)
        b = conn.gen_lineitem(cols, lo, hi)
        n = b.num_rows
        arrs = []
        for c, dt in zip(b.columns, np_dtypes):
            a = np.asarray(c.values)[:n].astype(dt)
            pad = np.zeros(cap, dt)
            pad[:n] = a
            arrs.append(pad)
        chunks.append(tuple(arrs) + (n,))
    return chunks, cap


def bench_q9(scale: float, chunk_orders: int = 1 << 24):
    import jax
    import jax.numpy as jnp

    conn, green, ps_sk, ps_cost, s_nat, o_year = _q9_tables(scale)
    cols = ["l_partkey", "l_suppkey", "l_orderkey", "l_quantity",
            "l_extendedprice", "l_discount"]
    dts = [np.int32, np.int32, np.int32, np.float32, np.float32, np.float32]
    chunks, cap = _gen_lineitem_chunks(conn, cols, dts, chunk_orders)
    for ch in chunks:
        ch[2][:ch[-1]] -= 1  # l_orderkey -> 0-based dense index
    n_li = sum(ch[-1] for ch in chunks)
    resident = tuple(jnp.asarray(a) for a in
                     (green, ps_sk, ps_cost, s_nat, o_year))

    # device-only rows/s from the dependence-chained slope on one chunk
    c0 = chunks[0]
    args = resident + tuple(jnp.asarray(a) for a in c0[:-1]) + (
        jnp.asarray(c0[-1], jnp.int64),)

    def chained(k):
        def body(_, carry):
            a, acc = carry
            out = q9_step(*a[:5], a[5] + (acc - acc).astype(a[5].dtype),
                          *a[6:])
            return (a, acc + out[0].astype(jnp.float64))
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    device_s_chunk = _slope_time(chained, args)
    device_s = device_s_chunk * (n_li / max(c0[-1], 1))

    # streamed pass (all chunks through the one compiled program) for the
    # grouped/chunked-dispatch wall at scales past the single-program cap
    step = jax.jit(q9_step)
    np.asarray(step(*args[:-1], args[-1]))  # compile outside the wall
    sums = np.zeros(175)
    t0 = time.perf_counter()
    for ch in chunks:
        out = step(*resident, *(jnp.asarray(a) for a in ch[:-1]),
                   jnp.asarray(ch[-1], jnp.int64))
        sums += np.asarray(out, dtype=np.float64)
    stream_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    want = _cpu_q9(green, ps_sk, ps_cost, s_nat, o_year, chunks)
    cpu_s = time.perf_counter() - t0
    ok = bool(np.allclose(sums, want, rtol=2e-3, atol=1.0))
    rows = (len(green) + len(ps_sk) + len(s_nat) + len(o_year) + n_li)
    return {
        "metric": f"tpch_sf{scale:g}_q9_join_agg_rows_per_sec_per_chip",
        "value": round(rows / device_s, 1), "unit": "rows/s",
        "vs_baseline": round((rows / device_s) / (rows / cpu_s), 3),
        "streamed_rows_per_sec": round(rows / stream_s, 1),
        "chunks": len(chunks),
        "parity": ok,
    }


# ---------------------------------------------------------------------------
# Config 5: TPC-H Q17 (part filter + correlated per-part avg + agg)
# ---------------------------------------------------------------------------

def _q17_tables(scale: float):
    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=scale).generator
    P = conn.n_part
    part = conn.gen_part(["p_brand", "p_container"], 1, P + 1)
    bcol, ccol = part.columns
    bc = bcol.dictionary.code_of("Brand#23")
    cc = ccol.dictionary.code_of("MED BOX")
    mask = np.zeros(P + 1, bool)
    mask[1:] = ((np.asarray(bcol.values) == bc)
                & (np.asarray(ccol.values) == cc))
    return conn, mask


def q17_passA(sumq, cnt, pk, qty, n_rows):
    """Accumulate per-part quantity sum/count (the correlated
    avg(l_quantity) subquery's aggregation) into donated accumulators."""
    import jax.numpy as jnp

    live = jnp.arange(pk.shape[0]) < n_rows
    idx = jnp.where(live, pk, 0)
    return (sumq.at[idx].add(jnp.where(live, qty, jnp.float32(0))),
            cnt.at[idx].add(live.astype(jnp.float32)))


def q17_passB(sumq, cnt, mask, pk, qty, price, n_rows):
    import jax.numpy as jnp

    live = jnp.arange(pk.shape[0]) < n_rows
    avg = sumq[pk] / jnp.maximum(cnt[pk], jnp.float32(1))
    sel = live & mask[pk] & (qty < 0.2 * avg)
    return jnp.where(sel, price, jnp.float32(0)).sum()


def q17_step(mask, pk, qty, price, n_rows):
    """Single-program Q17 join+agg stage (fits one chunk): per-part
    avg(l_quantity) via scatter-add over the partkey domain, then the
    filtered price sum — the reference's join + correlated-subquery plan
    (BenchmarkSuite.java:33) with the hash tables replaced by the dense
    part domain."""
    import jax.numpy as jnp

    P1 = mask.shape[0]
    sumq, cnt = q17_passA(jnp.zeros(P1, jnp.float32),
                          jnp.zeros(P1, jnp.float32), pk, qty, n_rows)
    return q17_passB(sumq, cnt, mask, pk, qty, price, n_rows) / 7.0


def _cpu_q17(mask, chunks):
    P1 = len(mask)
    sumq = np.zeros(P1)
    cnt = np.zeros(P1)
    for pk, qty, price, n in chunks:
        sumq += np.bincount(pk[:n], weights=qty[:n], minlength=P1)
        cnt += np.bincount(pk[:n], minlength=P1)
    total = 0.0
    for pk, qty, price, n in chunks:
        avg = sumq[pk[:n]] / np.maximum(cnt[pk[:n]], 1)
        sel = mask[pk[:n]] & (qty[:n] < 0.2 * avg)
        total += float(price[:n][sel].astype(np.float64).sum())
    return total / 7.0


def bench_q17(scale: float, chunk_orders: int = 1 << 24):
    import jax
    import jax.numpy as jnp

    conn, mask = _q17_tables(scale)
    cols = ["l_partkey", "l_quantity", "l_extendedprice"]
    dts = [np.int32, np.float32, np.float32]
    chunks, cap = _gen_lineitem_chunks(conn, cols, dts, chunk_orders)
    n_li = sum(ch[-1] for ch in chunks)
    mask_d = jnp.asarray(mask)

    c0 = chunks[0]
    args = (mask_d,) + tuple(jnp.asarray(a) for a in c0[:-1]) + (
        jnp.asarray(c0[-1], jnp.int64),)

    def chained(k):
        def body(_, carry):
            a, acc = carry
            s = q17_step(a[0], a[1] + (acc - acc).astype(a[1].dtype),
                         *a[2:])
            return (a, acc + s.astype(jnp.float64))
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    device_s_chunk = _slope_time(chained, args)
    device_s = device_s_chunk * (n_li / max(c0[-1], 1))

    # streamed two-pass (device-resident accumulators, donated)
    passA = jax.jit(q17_passA, donate_argnums=(0, 1))
    passB = jax.jit(q17_passB)
    P1 = mask.shape[0]
    wa, wb = passA(jnp.zeros(P1, jnp.float32),  # compile outside the wall
                   jnp.zeros(P1, jnp.float32), args[1], args[2], args[-1])
    float(passB(wa, wb, mask_d, *args[1:]))
    del wa, wb
    t0 = time.perf_counter()
    sumq = jnp.zeros(P1, jnp.float32)
    cnt = jnp.zeros(P1, jnp.float32)
    for ch in chunks:
        sumq, cnt = passA(sumq, cnt, jnp.asarray(ch[0]),
                          jnp.asarray(ch[1]), jnp.asarray(ch[-1], jnp.int64))
    got = 0.0
    for ch in chunks:
        got += float(passB(sumq, cnt, mask_d,
                           *(jnp.asarray(a) for a in ch[:-1]),
                           jnp.asarray(ch[-1], jnp.int64)))
    got /= 7.0
    stream_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    want = _cpu_q17(mask, chunks)
    cpu_s = time.perf_counter() - t0
    ok = bool(np.isclose(got, want, rtol=1e-3))
    rows = n_li + (P1 - 1)
    return {
        "metric": f"tpch_sf{scale:g}_q17_join_agg_rows_per_sec_per_chip",
        "value": round(rows / device_s, 1), "unit": "rows/s",
        "vs_baseline": round((rows / device_s) / (rows / cpu_s), 3),
        "streamed_rows_per_sec": round(rows / stream_s, 1),
        "chunks": len(chunks),
        "parity": ok,
    }


# ---------------------------------------------------------------------------
# Config 3b: TPC-H Q3 at scales past the single-program cap, as k
# order-aligned chunk dispatches through ONE compiled program (the
# grouped-execution / P9 idea applied to the bench: each device program
# stays under the tunnel toolchain's accepted size)
# ---------------------------------------------------------------------------

def q3_chunk_step(sel_ord, okey0, price, disc, ship, n_rows):
    """Per-chunk Q3 core: lineitems of any order are entirely within one
    chunk (order-aligned generation), so per-order revenue and the
    chunk-local top-10 are exact; the cross-chunk merge is a host top-10
    of k*10 candidates."""
    import jax
    import jax.numpy as jnp

    live = jnp.arange(okey0.shape[0]) < n_rows
    sel_li = live & (ship > Q3_DATE) & sel_ord[okey0]
    contrib = jnp.where(sel_li, price * (1.0 - disc), jnp.float32(0))
    rev = contrib
    for j in range(1, 7):
        shifted = jnp.concatenate(
            [jnp.zeros(j, contrib.dtype), contrib[:-j]])
        same = jnp.concatenate(
            [jnp.zeros(j, bool), okey0[j:] == okey0[:-j]])
        rev = rev + jnp.where(same, shifted, 0)
    end = jnp.concatenate([okey0[1:] != okey0[:-1], jnp.ones(1, bool)])
    rev = jnp.where(end & live, rev, jnp.float32(-1.0))
    B = 1024
    pad = (-rev.shape[0]) % B
    r2 = jnp.pad(rev, (0, pad), constant_values=-1.0).reshape(B, -1)
    tv, ti = jax.lax.top_k(r2, 10)
    base = (jnp.arange(B) * r2.shape[1])[:, None]
    cv, ci = jax.lax.top_k(tv.reshape(-1), 10)
    pos = (base + ti).reshape(-1)[ci]
    return cv, okey0[jnp.clip(pos, 0, okey0.shape[0] - 1)] + 1


def bench_q3_chunked(scale: float, chunk_orders: int = 1 << 24):
    import jax
    import jax.numpy as jnp

    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=scale).generator
    n_cust, n_ord = conn.n_customer, conn.n_orders
    cust = conn.gen_customer(["c_custkey", "c_mktsegment"], 1, n_cust + 1)
    seg = cust.columns[1]
    building_code = seg.dictionary.code_of("BUILDING")
    cust_building = np.zeros(n_cust + 1, bool)
    cust_building[np.asarray(cust.columns[0].values)] = (
        np.asarray(seg.values) == building_code)
    orders = conn.gen_orders(["o_custkey", "o_orderdate"], 1, n_ord + 1)
    ocust = np.asarray(orders.columns[0].values).astype(np.int32)
    odate = np.asarray(orders.columns[1].values).astype(np.int32)

    cols = ["l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"]
    dts = [np.int32, np.float32, np.float32, np.int32]
    chunks, cap = _gen_lineitem_chunks(conn, cols, dts, chunk_orders)
    for ch in chunks:
        ch[0][:ch[-1]] -= 1  # okey -> 0-based
    n_li = sum(ch[-1] for ch in chunks)
    rows = n_cust + n_ord + n_li

    # join #1 (customer⨝orders) once, on device, result resident
    sel_prog = jax.jit(lambda cb, oc, od: cb[oc] & (od < Q3_DATE))
    step = jax.jit(q3_chunk_step)
    # compile both programs outside the streamed wall
    sel_ord = sel_prog(jnp.asarray(cust_building), jnp.asarray(ocust),
                       jnp.asarray(odate))
    c0w = chunks[0]
    np.asarray(step(sel_ord, *(jnp.asarray(a) for a in c0w[:-1]),
                    jnp.asarray(c0w[-1], jnp.int64))[0])
    t0 = time.perf_counter()
    sel_ord = sel_prog(jnp.asarray(cust_building), jnp.asarray(ocust),
                       jnp.asarray(odate))
    cands_v, cands_k = [], []
    for ch in chunks:
        cv, ck = step(sel_ord, *(jnp.asarray(a) for a in ch[:-1]),
                      jnp.asarray(ch[-1], jnp.int64))
        cands_v.append(np.asarray(cv))
        cands_k.append(np.asarray(ck))
    stream_s = time.perf_counter() - t0
    allv = np.concatenate(cands_v)
    top = np.argsort(-allv, kind="stable")[:10]
    got = np.sort(allv[top])[::-1]

    # device-only slope on one resident chunk, scaled to the full input
    c0 = chunks[0]
    args = (sel_ord,) + tuple(jnp.asarray(a) for a in c0[:-1]) + (
        jnp.asarray(c0[-1], jnp.int64),)

    def chained(k):
        def body(_, carry):
            a, acc = carry
            out = q3_chunk_step(a[0], a[1] + (acc - acc).astype(a[1].dtype),
                                *a[2:])
            return (a, acc + out[0][0].astype(jnp.float64))
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    device_s = _slope_time(chained, args) * (n_li / max(c0[-1], 1))

    # CPU oracle (f64, chunked bincount over the dense orderkey domain)
    t0 = time.perf_counter()
    rev = np.zeros(n_ord)
    sel_np = cust_building[ocust] & (odate < Q3_DATE)
    for ch in chunks:
        okey0, price, disc, ship, n = ch
        s = (ship[:n] > Q3_DATE) & sel_np[okey0[:n]]
        contrib = np.where(s, price[:n].astype(np.float64)
                           * (1.0 - disc[:n].astype(np.float64)), 0.0)
        rev += np.bincount(okey0[:n], weights=contrib, minlength=n_ord)
    want = np.sort(rev[np.argsort(-rev, kind="stable")[:10]])[::-1]
    cpu_s = time.perf_counter() - t0
    ok = bool(np.allclose(got, want, rtol=1e-4))
    return {
        "metric": f"tpch_sf{scale:g}_q3_join_agg_rows_per_sec_per_chip",
        "value": round(rows / device_s, 1), "unit": "rows/s",
        "vs_baseline": round((rows / device_s) / (rows / cpu_s), 3),
        "streamed_rows_per_sec": round(rows / stream_s, 1),
        "chunks": len(chunks), "chunked": True,
        "parity": ok,
    }


# ---------------------------------------------------------------------------
# Config 6: the SHIPPED ENGINE path (SQL text -> planner -> operator tier)
# ---------------------------------------------------------------------------

ENGINE_Q1 = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc, count(*) as count_order
from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus
"""

ENGINE_Q6 = """
select sum(l_extendedprice * l_discount) as revenue from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1994-01-01' + interval '1' year
  and l_discount between 0.05 and 0.07 and l_quantity < 24
"""


def bench_engine_q1q6(scale: float):
    """TPC-H Q1 + Q6 through the SHIPPED SQL runner (parser -> optimizer
    -> operator tier with pipeline fusion), so the artifact measures what
    the engine actually executes — not hand-built kernels.  Reports warm
    rows/s per query, the fused-vs-unfused wall ratio, and the jit
    dispatch counters the fusion tier halves (ROADMAP #10)."""
    import dataclasses as dc

    from presto_tpu.config import EngineConfig
    from presto_tpu.localrunner import LocalQueryRunner

    runner = LocalQueryRunner.tpch(scale=scale)
    runner_off = LocalQueryRunner.tpch(scale=scale, config=dc.replace(
        EngineConfig(), pipeline_fusion=False))
    n_rows = runner.execute(
        "select count(*) from lineitem").rows[0][0]

    def timed(r, sql):
        t0 = time.perf_counter()
        r.execute(sql)                      # compile + warm caches
        cold_s = time.perf_counter() - t0
        cold_jit = r._last_task.jit_counters()
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            res = r.execute(sql)
            best = min(best, time.perf_counter() - t0)
        warm = r._last_task.jit_counters()
        # compile-vs-execute split (PR 9 attribution): cold wall is
        # compile-dominated, warm wall must carry ZERO compile ns —
        # nonzero warm compile means a cache key churns per execution
        warm["cold_s"] = round(cold_s, 4)
        warm["cold_compile_ms"] = round(cold_jit["compile_ns"] / 1e6, 1)
        warm["warm_compile_ms"] = round(warm["compile_ns"] / 1e6, 3)
        return best, res, warm

    q1_s, q1_res, q1_jit = timed(runner, ENGINE_Q1)
    q6_s, q6_res, q6_jit = timed(runner, ENGINE_Q6)
    q1_off_s, q1_off_res, q1_off_jit = timed(runner_off, ENGINE_Q1)
    q6_off_s, q6_off_res, _ = timed(runner_off, ENGINE_Q6)

    def close(a, b):
        if len(a) != len(b):
            return False
        for ra, rb in zip(sorted(a, key=repr), sorted(b, key=repr)):
            for va, vb in zip(ra, rb):
                if isinstance(va, float):
                    if not np.isclose(va, vb, rtol=1e-6):
                        return False
                elif va != vb:
                    return False
        return True

    parity = close(q1_res.rows, q1_off_res.rows) and \
        close(q6_res.rows, q6_off_res.rows)
    return {
        "metric": f"tpch_sf{scale:g}_q1_engine_rows_per_sec",
        "value": round(n_rows / q1_s, 1), "unit": "rows/s",
        # baseline for the engine path = the same engine with pipeline
        # fusion off (per-operator dispatch, the pre-fusion engine)
        "vs_baseline": round(q1_off_s / q1_s, 3),
        "engine_path": True,
        "q6_rows_per_sec": round(n_rows / q6_s, 1),
        "q6_speedup_vs_unfused": round(q6_off_s / q6_s, 3),
        "jit_dispatches": {"q1_fused": q1_jit["dispatches"],
                           "q1_unfused": q1_off_jit["dispatches"],
                           "q6_fused": q6_jit["dispatches"]},
        # compile-vs-execute attribution (jit_counters()['compile_ns']):
        # the warm number is the regression canary — it was ~400 ms/run
        # before PR 10 pinned the scan dictionaries (a fused-segment
        # cache key churned per execution)
        "compile_split": {
            "q1_cold_s": q1_jit["cold_s"],
            "q1_cold_compile_ms": q1_jit["cold_compile_ms"],
            "q1_warm_compile_ms": q1_jit["warm_compile_ms"],
            "q6_warm_compile_ms": q6_jit["warm_compile_ms"]},
        "parity": parity,
    }


def bench_engine_q3q9(scale: float):
    """Join-heavy TPC-H Q3 + Q9 through the SHIPPED LocalQueryRunner —
    the tracked number for the device-resident hash tier (PagesHash
    probe absorbed into fused segments + GroupByHash aggregation
    state).  Baseline = the same engine with every PR 10 kernel off
    (hash_groupby_enabled / device_join_probe / fusion_final_merge /
    prereduce_cost_based = false, i.e. the PR 9 lowering), so
    vs_baseline prices the hash tier directly; parity is checked
    against that baseline's rows."""
    import dataclasses as dc
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpch_queries import QUERIES

    from presto_tpu.config import EngineConfig
    from presto_tpu.localrunner import LocalQueryRunner

    runner = LocalQueryRunner.tpch(scale=scale)
    runner_off = LocalQueryRunner.tpch(scale=scale, config=dc.replace(
        EngineConfig(), hash_groupby_enabled=False,
        device_join_probe=False, fusion_final_merge=False,
        prereduce_cost_based=False))
    n_rows = runner.execute(
        "select count(*) from lineitem").rows[0][0]

    def timed(r, sql):
        t0 = time.perf_counter()
        r.execute(sql)
        cold_s = time.perf_counter() - t0
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            res = r.execute(sql)
            best = min(best, time.perf_counter() - t0)
        jit = r._last_task.jit_counters()
        jit["cold_s"] = round(cold_s, 4)
        jit["warm_compile_ms"] = round(jit["compile_ns"] / 1e6, 3)
        return best, res, jit

    q3_s, q3_res, q3_jit = timed(runner, QUERIES[3])
    q9_s, q9_res, q9_jit = timed(runner, QUERIES[9])
    q3_off_s, q3_off_res, q3_off_jit = timed(runner_off, QUERIES[3])
    q9_off_s, q9_off_res, q9_off_jit = timed(runner_off, QUERIES[9])

    def close(a, b):
        if len(a) != len(b):
            return False
        for ra, rb in zip(sorted(a, key=repr), sorted(b, key=repr)):
            for va, vb in zip(ra, rb):
                if isinstance(va, float) and isinstance(vb, float):
                    if not np.isclose(va, vb, rtol=1e-6):
                        return False
                elif va != vb:
                    return False
        return True

    parity = close(q3_res.rows, q3_off_res.rows) and \
        close(q9_res.rows, q9_off_res.rows)
    return {
        "metric": f"tpch_sf{scale:g}_q3_engine_rows_per_sec",
        "value": round(n_rows / q3_s, 1), "unit": "rows/s",
        "vs_baseline": round(q3_off_s / q3_s, 3),
        "engine_path": True, "join_heavy": True,
        "q9_rows_per_sec": round(n_rows / q9_s, 1),
        "q9_speedup_vs_pr9_path": round(q9_off_s / q9_s, 3),
        "jit_dispatches": {
            "q3_hash": q3_jit["dispatches"],
            "q3_pr9": q3_off_jit["dispatches"],
            "q9_hash": q9_jit["dispatches"],
            "q9_pr9": q9_off_jit["dispatches"]},
        "compile_split": {
            "q3_cold_s": q3_jit["cold_s"],
            "q3_warm_compile_ms": q3_jit["warm_compile_ms"],
            "q9_warm_compile_ms": q9_jit["warm_compile_ms"]},
        "parity": parity,
    }


def bench_mesh_q1q6(scale: float):
    """TPC-H Q1 + Q6 through the DISTRIBUTED tier: a real 2-worker
    cluster (DistributedQueryRunner — coordinator + workers over HTTP)
    vs the single-process engine on the same data.  PR 11: the cluster
    runs with ``mesh_device_exchange`` ON — co-resident fragments lower
    to ONE SPMD program with in-program collectives instead of
    serde+HTTP (ROADMAP #2 acceptance: mesh >= 1.0x the LOCAL engine
    path; PR 10 measured 0.73x on the wire tier).  A second knobs-off
    cluster keeps measuring the PR 10 HTTP plane so the wire-tier trend
    stays visible."""
    import dataclasses as _dc

    from presto_tpu.config import DEFAULT
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.server.dqr import DistributedQueryRunner

    def close(a, b):
        if len(a) != len(b):
            return False
        for ra, rb in zip(sorted(a, key=repr), sorted(b, key=repr)):
            for va, vb in zip(ra, rb):
                if isinstance(va, float) and isinstance(vb, float):
                    if not np.isclose(va, vb, rtol=1e-6):
                        return False
                elif va != vb:
                    return False
        return True

    local = LocalQueryRunner.tpch(scale=scale)
    n_rows = local.execute("select count(*) from lineitem").rows[0][0]

    def timed_local(sql):
        local.execute(sql)
        best = float("inf")
        res = None
        for _ in range(2):
            t0 = time.perf_counter()
            res = local.execute(sql)
            best = min(best, time.perf_counter() - t0)
        return best, res

    def timed_cluster(dqr, sql, runs=2):
        """Warm, then time ``runs`` executions; returns (times, res).
        The headline keeps best-of-N; the telemetry/checkpoint extras
        damp run-to-run noise the PR 13 way — MEDIAN of 3 plus a
        ``noise_band`` annotation for perf_regress."""
        dqr.execute(sql)                  # compile + warm caches
        times, res = [], None
        for _ in range(runs):
            t0 = time.perf_counter()
            res = dqr.execute(sql)
            times.append(time.perf_counter() - t0)
        return times, res

    def median(times):
        return sorted(times)[len(times) // 2]

    dev_cfg = _dc.replace(DEFAULT, mesh_device_exchange=True)
    with DistributedQueryRunner.tpch(scale=scale, n_workers=2,
                                     config=dev_cfg) as dqr:
        q1_times, q1_res = timed_cluster(dqr, ENGINE_Q1, runs=3)
        q6_times, q6_res = timed_cluster(dqr, ENGINE_Q6, runs=3)
        q1_s, q6_s = min(q1_times), min(q6_times)
        last = list(dqr.coordinator.queries.values())[-1]
        device_engaged = set(last.exchange_modes) == {"device"}
        beacon_samples = len(last.timeseries)
    # the SAME collective tier with progress beacons traced OUT of the
    # program (PR 12 default ON): the on-vs-off delta IS the telemetry
    # overhead, tracked so perf_regress can see it drift
    nb_cfg = _dc.replace(dev_cfg, mesh_progress_beacons=False)
    with DistributedQueryRunner.tpch(scale=scale, n_workers=2,
                                     config=nb_cfg) as dqr_nb:
        q1_nb_times, _r1 = timed_cluster(dqr_nb, ENGINE_Q1, runs=3)
        q6_nb_times, _r6 = timed_cluster(dqr_nb, ENGINE_Q6, runs=3)
    # PR 17 mid-program fault tolerance: the same tier with boundary
    # checkpoints ON — each fragment group runs as its own SPMD program
    # and its output is write-through'd into the spool, so the
    # on-vs-off delta IS the checkpoint overhead a user pays for
    # partial-state resume
    ck_cfg = _dc.replace(dev_cfg, mesh_checkpoint_boundaries=True)
    with DistributedQueryRunner.tpch(scale=scale, n_workers=2,
                                     config=ck_cfg) as dqr_ck:
        q1_ck_times, c1_res = timed_cluster(dqr_ck, ENGINE_Q1, runs=3)
        q6_ck_times, c6_res = timed_cluster(dqr_ck, ENGINE_Q6, runs=3)
        last_ck = list(dqr_ck.coordinator.queries.values())[-1]
        ck_info = getattr(last_ck, "device_exchange_info", None) or {}
    with DistributedQueryRunner.tpch(scale=scale, n_workers=2) as http:
        h1_times, _h1 = timed_cluster(http, ENGINE_Q1)
        h6_times, _h6 = timed_cluster(http, ENGINE_Q6)
        h1_s, h6_s = min(h1_times), min(h6_times)
    q1_local_s, q1_local = timed_local(ENGINE_Q1)
    q6_local_s, q6_local = timed_local(ENGINE_Q6)
    parity = close(q1_res.rows, q1_local.rows) and \
        close(q6_res.rows, q6_local.rows)
    ck_parity = close(c1_res.rows, q1_local.rows) and \
        close(c6_res.rows, q6_local.rows)
    q1_med, q6_med = median(q1_times), median(q6_times)
    q1_nb_s, q6_nb_s = median(q1_nb_times), median(q6_nb_times)
    q1_ck_s, q6_ck_s = median(q1_ck_times), median(q6_ck_times)
    return {
        "metric": f"tpch_sf{scale:g}_q1_mesh_2worker_rows_per_sec",
        "value": round(n_rows / q1_s, 1), "unit": "rows/s",
        # baseline = the single-process engine on the same data: >= 1.0
        # means distribution now buys more than it costs
        "vs_baseline": round(q1_local_s / q1_s, 3),
        "engine_path": True, "distributed": True, "workers": 2,
        "device_exchange": device_engaged,
        "q6_rows_per_sec": round(n_rows / q6_s, 1),
        "q6_vs_local": round(q6_local_s / q6_s, 3),
        # the PR 10 wire tier on the same cluster shape (trend line)
        "http_plane": {
            "q1_vs_local": round(q1_local_s / h1_s, 3),
            "q6_vs_local": round(q6_local_s / h6_s, 3),
        },
        # PR 12 telemetry overhead: wall with progress beacons traced
        # into the program (the shipped default) vs the beacon-free
        # PR 11 program; ratio > 1 = beacons cost wall.  PR 17: both
        # sides are MEDIAN-of-3 with the PR 13 noise_band annotation —
        # the 1-core CI host swings single-shot overhead ratios well
        # past any real beacon cost, so perf_regress gates the trend
        "telemetry": {
            "beacons_on_q1_ms": round(q1_med * 1000, 2),
            "beacons_off_q1_ms": round(q1_nb_s * 1000, 2),
            "beacons_on_q6_ms": round(q6_med * 1000, 2),
            "beacons_off_q6_ms": round(q6_nb_s * 1000, 2),
            "overhead_q1": round(q1_med / max(q1_nb_s, 1e-9), 3),
            "overhead_q6": round(q6_med / max(q6_nb_s, 1e-9), 3),
            "beacon_samples_q6": beacon_samples,
            "runs": 3, "aggregation": "median", "noise_band": 0.6,
        },
        # PR 17 checkpoint overhead: the same tier with
        # mesh_checkpoint_boundaries ON (per-group SPMD programs +
        # spool write-through) vs the one-program default; ratio > 1 =
        # what resume-ability costs when nothing fails
        "checkpoints": {
            "ckpt_on_q1_ms": round(q1_ck_s * 1000, 2),
            "ckpt_on_q6_ms": round(q6_ck_s * 1000, 2),
            "overhead_q1": round(q1_ck_s / max(q1_med, 1e-9), 3),
            "overhead_q6": round(q6_ck_s / max(q6_med, 1e-9), 3),
            "groups_q6": ck_info.get("checkpoint_groups", 0),
            "bytes_q6": ck_info.get("checkpoint_bytes", 0),
            "parity": ck_parity,
            "runs": 3, "aggregation": "median", "noise_band": 0.6,
        },
        "parity": parity,
    }


_SHARDED_JOIN_SQL = (
    "select o_orderpriority, count(*) as c, sum(l_extendedprice) as s "
    "from lineitem, orders where l_orderkey = o_orderkey "
    "group by o_orderpriority order by o_orderpriority")


def _sharded_join_model(n_probe: int, n_build: int, ncols: int,
                        nparts: int, buckets: int):
    """Modeled per-shard peak bytes of the mesh join, mirroring the
    capacity formulas in parallel/sqlmesh.py (cap_scale=1): exchange
    receive buffers (sharded sizing when nparts > 1), the per-shard
    PagesHash table, the bucket-sequential working buffers, and the
    match-expansion output.  9 bytes/column-row (8 value + 1 valid),
    int64 index buffers.  ``nparts=buckets=1`` models the single-device
    unbucketed build the P8+P9 path exists to break past."""
    from presto_tpu.batch import next_bucket

    if nparts > 1:
        pcap = next_bucket(max(8, (2 * n_probe) // nparts))
        bcap = next_bucket(max(8, (2 * n_build) // nparts))
    else:
        pcap = next_bucket(max(8, n_probe))
        bcap = next_bucket(max(8, n_build))
    table_cap = next_bucket(2 * bcap, minimum=16)
    out_cap = next_bucket(max(pcap, bcap))
    if buckets > 1:
        wb = min(next_bucket(max(8, (2 * bcap) // buckets)), bcap)
        wp = min(next_bucket(max(8, (2 * pcap) // buckets)), pcap)
        we = min(next_bucket(max(8, (2 * max(pcap, bcap)) // buckets)),
                 out_cap)
    else:
        wb, wp, we = bcap, pcap, out_cap
    col = 9                      # value + valid bytes per row per column
    idx = 8
    exchange_bytes = (pcap + bcap) * ncols * col
    table_bytes = table_cap * (2 * idx + 8 + 1 + 1)  # words+starts+cnt..
    working_bytes = (wb + wp) * (ncols * col + idx) + we * 3 * idx
    out_bytes = out_cap * (ncols * col + 2 * idx)
    return {
        "probe_cap": pcap, "build_cap": bcap, "table_cap": table_cap,
        "bucket_caps": [wb, wp, we], "out_cap": out_cap,
        "total_bytes": exchange_bytes + table_bytes + working_bytes
        + out_bytes,
    }


def _sharded_join_inner(scale: float):
    """Runs inside the 8-virtual-device subprocess: the P8+P9
    acceptance config — lineitem JOIN orders with the build FORCED
    partitioned (join_distribution_type), the PagesHash build table
    sharded across 8 shards' HBM, probes routed by the hash-exchange
    all_to_all, and 8 hash buckets run sequentially through the sharded
    join."""
    import dataclasses as _dc

    from presto_tpu.config import DEFAULT
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.parallel.sqlmesh import MeshQueryRunner

    P, B = 8, 8
    local = LocalQueryRunner.tpch(scale=scale)
    n_probe = local.execute("select count(*) from lineitem").rows[0][0]
    n_build = local.execute("select count(*) from orders").rows[0][0]
    want = local.execute(_SHARDED_JOIN_SQL).rows
    cfg = _dc.replace(
        DEFAULT, partitioned_join_build=True, grouped_mesh_execution=B,
        device_join_probe_max_build_rows=1,
        join_distribution_type="partitioned")
    mesh = MeshQueryRunner.tpch(scale=scale, n_devices=P, config=cfg)
    mesh.execute(_SHARDED_JOIN_SQL)          # trace + compile
    best = float("inf")
    res = None
    for _ in range(2):
        t0 = time.perf_counter()
        res = mesh.execute(_SHARDED_JOIN_SQL)
        best = min(best, time.perf_counter() - t0)
    info = mesh.last_run_info

    def close(a, b):
        if len(a) != len(b):
            return False
        for ra, rb in zip(sorted(a, key=repr), sorted(b, key=repr)):
            for va, vb in zip(ra, rb):
                if isinstance(va, float) and isinstance(vb, float):
                    if not np.isclose(va, vb, rtol=1e-6):
                        return False
                elif va != vb:
                    return False
        return True

    # HBM overflow model (documented acceptance): capacity formulas
    # mirror parallel/sqlmesh.py; bytes scale ~linearly with the scale
    # factor, so dividing a real 16 GiB v5e HBM by the per-SF bytes
    # gives each path's maximum holdable scale factor.  The run
    # executes at a budget scaled to SF_CLAIM — a scale factor the
    # model puts PAST the single-device limit and INSIDE the sharded
    # one: the single-device build provably overflows it while the
    # 8-shard x 8-bucket partitioned+grouped path fits.
    ncols = 3                      # l_orderkey, l_extendedprice, o_* keys
    single = _sharded_join_model(n_probe, n_build, ncols, 1, 1)
    sharded = _sharded_join_model(n_probe, n_build, ncols, P, B)
    hbm = 16 * (1 << 30)
    sf_max_single = round(hbm / (single["total_bytes"] / scale), 1)
    sf_max_sharded = round(hbm / (sharded["total_bytes"] / scale), 1)
    sf_claim = 30.0
    budget = int(hbm * scale / sf_claim)
    tiers = info.get("kernel_tiers", [])
    grouped_pages = sum(1 for t in tiers
                        if t.startswith("grouped join")
                        and t.endswith("pages_hash"))
    return {
        "metric": f"tpch_sf{scale:g}_sharded_join_rows_per_sec",
        "value": round(n_probe / best, 1), "unit": "rows/s",
        "vs_baseline": 1.0,
        "engine_path": True, "distributed": True,
        "nparts": P, "buckets": B,
        "parity": close(res.rows, want),
        "exchange_modes": info.get("exchange_modes", {}),
        "grouped_pages_hash_buckets": grouped_pages,
        "hbm_model": {
            "note": (f"16 GiB v5e budget scaled to SF{sf_claim:g}: the "
                     "single-device unbucketed build overflows it, the "
                     "8-shard x 8-bucket path fits; sf_max_* = largest "
                     "SF each path holds under a real 16 GiB HBM"),
            "budget_bytes": budget,
            "single_device_bytes": single["total_bytes"],
            "single_device_overflows": single["total_bytes"] > budget,
            "per_shard_bucketed_bytes": sharded["total_bytes"],
            "sharded_fits": sharded["total_bytes"] < budget,
            "sf_max_single_16gib": sf_max_single,
            "sf_max_sharded_16gib": sf_max_sharded,
            "single": single, "sharded": sharded,
        },
    }


def bench_mesh_sharded_join(scale: float):
    """P8 + P9 acceptance config (ROADMAP #2): the partitioned lookup
    source (PagesHash build sharded across 8 shards' HBM, probes routed
    by all_to_all) plus bucket-sequential grouped execution, at a scale
    factor where the single-device unbucketed build provably overflows
    the modeled per-device HBM budget (extras carry the model).  Runs
    in a subprocess so the 8-virtual-device XLA host platform doesn't
    perturb the other configs' device topology."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--sharded-join-inner", str(scale)],
        env=env, capture_output=True, text=True, timeout=1200)
    for ln in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(ln)
        except ValueError:
            continue
    return {"metric": f"bench_mesh_sharded_join_sf{scale:g}_failed",
            "error": (r.stderr or r.stdout)[-300:]}


def _bench_tpcds_mesh(scale: float, spooling: bool):
    import dataclasses as _dc
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from tpcds_queries import QUERIES as DS

    from presto_tpu.config import DEFAULT
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.server.dqr import DistributedQueryRunner

    reg = ConnectorRegistry()
    reg.register("tpcds", TpcdsConnector(scale=scale))
    local = LocalQueryRunner(reg, "tpcds")
    n_rows = local.execute(
        "select count(*) from tpcds.catalog_sales").rows[0][0] + \
        local.execute("select count(*) from tpcds.web_sales").rows[0][0]

    def norm(rows):
        return sorted(tuple(round(v, 4) if isinstance(v, float) else v
                            for v in r) for r in rows)

    cfg = _dc.replace(DEFAULT, exchange_spooling_enabled=spooling)
    # the spooled config swings wildly across single-shot runs
    # (158-742 rows/s observed in the PR 12 variance investigation:
    # write-through timing vs the 0.1s stats sampler beats) — report
    # the MEDIAN of 3 mesh executions per query so perf_regress
    # --check gates on the trend, not the noise
    runs = 3 if spooling else 1
    out = {}
    with DistributedQueryRunner.tpcds(scale=scale, n_workers=2,
                                      config=cfg) as dqr:
        for qn in (72, 95):
            t0 = time.perf_counter()
            want = local.execute(DS[qn]).rows
            t_local = time.perf_counter() - t0
            mesh_times, parity = [], True
            for _ in range(runs):
                t0 = time.perf_counter()
                got = dqr.execute(DS[qn]).rows
                mesh_times.append(time.perf_counter() - t0)
                parity = parity and norm(got) == norm(want)
            t_mesh = sorted(mesh_times)[len(mesh_times) // 2]
            out[qn] = {"mesh_s": round(t_mesh, 3),
                       "local_s": round(t_local, 3),
                       "mesh_runs_s": [round(t, 3)
                                       for t in mesh_times],
                       "parity": parity}
    suffix = "_spooled" if spooling else ""
    row = {
        "metric": f"tpcds_sf{scale:g}_q72q95_mesh_2worker"
                  f"{suffix}_fact_rows_per_sec",
        "value": round(n_rows / (out[72]["mesh_s"] + out[95]["mesh_s"]),
                       1),
        "unit": "rows/s", "vs_baseline": round(
            (out[72]["local_s"] + out[95]["local_s"])
            / (out[72]["mesh_s"] + out[95]["mesh_s"]), 3),
        "engine_path": True, "distributed": True, "workers": 2,
        "exchange_spooling": spooling,
        "runs": runs, "aggregation": "median" if runs > 1 else "single",
        "q72": out[72], "q95": out[95],
        "parity": out[72]["parity"] and out[95]["parity"],
    }
    if spooling:
        # documented run-to-run spread of this config on the 1-core CI
        # host (PR 12 investigation: 158-742 rows/s across reruns of
        # one tree) — perf_regress widens its gate to this band for
        # THIS config only, so the trajectory check gates on the trend
        row["noise_band"] = 0.6
    return row


def bench_tpcds_mesh_q72q95(scale: float):
    """TPC-DS Q72 + Q95 — the BASELINE.md multi-chip configs — through
    the DISTRIBUTED tier: a real 2-worker cluster with HTTP exchanges,
    parity-checked against the single-process engine on identical data
    (ROADMAP #3: the multi-chip proof beyond TPC-H, measured).
    Exchange spooling OFF: this row keeps measuring the PR 5-era
    in-memory data plane, so its trend stays comparable."""
    return _bench_tpcds_mesh(scale, spooling=False)


def bench_tpcds_mesh_q72q95_spooled(scale: float):
    """The same mesh configs with the spooled exchange ON (write-through
    to the local-FS spool store): the delta against
    ``bench_tpcds_mesh_q72q95`` IS the spooling overhead, tracked as a
    number per round."""
    return _bench_tpcds_mesh(scale, spooling=True)


def bench_concurrent_qps(scale: float):
    """Serving-tier sustained QPS (tools/qps_run.py): N concurrent
    clients driving the mixed TPC-H/TPC-DS statement set against a live
    2-worker DistributedQueryRunner with resource-group admission
    engaged — QPS + p50/p95/p99 per concurrency level, exact-rows
    parity per client, plan-cache hit rate, and the zero-jit-compile
    proof for the second execution of a cached plan — plus the
    open-loop overload curve (bounded-pool dispatcher driven past
    saturation: goodput/shed/latency per arrival rate)."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import qps_run

    report = qps_run.run_qps(scale=scale, levels=(1, 2, 4, 8),
                             requests_per_client=6, mode="closed",
                             quiet=True)
    peak = max(lv["qps"] for lv in report["levels"])
    levels = []
    for lv in report["levels"]:
        row = {k: lv[k] for k in ("concurrency", "qps", "p50_ms",
                                  "p95_ms", "p99_ms", "parity")}
        row["plan_cache_hit_rate"] = lv["plan_cache"]["hit_rate"]
        levels.append(row)
    # hot-repeat tier (server/resultcache.py): the SAME dashboard-shape
    # worklist with the cross-query result cache on vs off — the on/off
    # ratio is the serving-tier headline a hit costs one spool lookup
    # instead of a full execution.  Parity is per request in both runs.
    hot = {}
    for label, rc in (("cache_on", True), ("cache_off", False)):
        rep = qps_run.run_qps(scale=scale, levels=(4,),
                              requests_per_client=10, mode="closed",
                              quiet=True, hot_repeat=True,
                              result_cache=rc)
        lv = rep["levels"][0]
        hot[label] = {
            "qps": lv["qps"], "p50_ms": lv["p50_ms"],
            "p95_ms": lv["p95_ms"], "parity": rep["parity"],
            "result_cache_hit_rate": rep["result_cache_hit_rate"],
            "result_cache_bytes_served":
                rep["result_cache_bytes_served"]}
    hot["speedup"] = round(
        hot["cache_on"]["qps"] / hot["cache_off"]["qps"], 2) \
        if hot["cache_off"]["qps"] else 0.0
    hot["parity"] = (hot["cache_on"]["parity"]
                     and hot["cache_off"]["parity"])
    # open-loop overload tier (server/dispatcher.py bounded pool):
    # arrivals PAST saturation must degrade to fast well-shaped
    # QUERY_QUEUE_FULL rejections with retry hints while goodput holds
    # — the graceful-degradation curve (goodput/shed/latency per rate)
    # 4s per level: at 2s the goodput ratio is dominated by queue
    # ramp/drain edge effects on the 1-core CI host (measured swings
    # 0.60-1.04 across reruns of one tree); the longer window keeps
    # the steady-state shed/goodput mix in charge of the number
    ov = qps_run.run_overload(scale=scale, pool_size=4, max_queued=8,
                              duration_s=4.0, quiet=True)
    overload = {
        "peak_qps": ov["peak_qps"],
        "dispatcher": ov["dispatcher"],
        "goodput_ratio_at_2x": ov["goodput_ratio_at_max"],
        "shed_total": ov["shed_total"],
        "graceful": ov["ok"],
        # "errors" carries samples of any non-shaped failure so a
        # parity=false artifact is diagnosable from the JSON alone
        "levels": [{k: lv[k] for k in (
            "rate_factor", "rate_per_s", "requests", "ok", "shed",
            "other", "goodput_qps", "shed_rate", "p50_ms", "p95_ms",
            "shed_p95_ms", "errors")} for lv in ov["levels"]],
    }
    return {
        "metric": f"tpcds_sf{scale:g}_concurrent_qps_peak",
        "value": peak, "unit": "qps",
        # scaling vs the single-client level: how much of the added
        # concurrency the serving tier converts into throughput
        "vs_baseline": round(peak / report["levels"][0]["qps"], 3)
        if report["levels"][0]["qps"] else 0.0,
        "engine_path": True, "distributed": True, "workers": 2,
        "levels": levels,
        "plan_cache_hit_rate": report["plan_cache_hit_rate"],
        "second_run_jit_compiles": report["second_run_jit_compiles"],
        "queries_queued": report["queries_queued"],
        "resource_groups": report["resource_groups"],
        "hot_repeat": hot,
        "overload": overload,
        # overload folds only its SHAPE requirement into parity (zero
        # non-error-shaped failures); the goodput ratio is a perf
        # property recorded in the curve, not a correctness gate
        "parity": report["parity"] and hot["parity"]
        and all(lv["other"] == 0 for lv in overload["levels"]),
    }


def bench_sqlite_baseline(scale: float):
    """External (non-self-authored) CPU baseline: the sqlite3 engine over
    IDENTICAL generated data, per BASELINE.md's measurement note — the
    'reference CPU engine' stand-in the builder did not write."""
    import sqlite3

    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=scale)
    db = sqlite3.connect(":memory:")
    for table, cols in (
        ("lineitem", ["l_orderkey", "l_quantity", "l_extendedprice",
                      "l_discount", "l_tax", "l_returnflag",
                      "l_linestatus", "l_shipdate"]),
    ):
        h = conn.get_table(table)
        schema = conn.table_schema(h)
        db.execute(f"create table {table} ("
                   + ", ".join(f"{c} NUMERIC" for c in cols) + ")")
        n = 0
        for split in conn.get_splits(h, 1):
            for b in conn.page_source(split, cols, 1 << 20):
                rows = b.to_pylist()
                db.executemany(
                    f"insert into {table} values "
                    f"({', '.join('?' * len(cols))})",
                    [[str(v) if not isinstance(v, (int, float)) else v
                      for v in r] for r in rows])
                n += b.num_rows
        db.commit()
    t0 = time.perf_counter()
    db.execute(
        "select l_returnflag, l_linestatus, sum(l_quantity), "
        "sum(l_extendedprice), sum(l_extendedprice*(1-l_discount)), "
        "sum(l_extendedprice*(1-l_discount)*(1+l_tax)), sum(l_discount), "
        "count(*) from lineitem where l_shipdate <= 10471 "
        "group by l_returnflag, l_linestatus").fetchall()
    q1_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    db.execute(
        "select sum(l_extendedprice*l_discount) from lineitem "
        f"where l_shipdate >= {Q6_DATE_LO} and l_shipdate < {Q6_DATE_HI} "
        f"and l_discount > {Q6_DISC_LO} and l_discount < {Q6_DISC_HI} "
        "and l_quantity < 24").fetchall()
    q6_s = time.perf_counter() - t0
    db.close()
    return {
        "metric": f"cpu_sqlite_sf{scale:g}_q1_rows_per_sec",
        "value": round(n / q1_s, 1), "unit": "rows/s",
        "vs_baseline": 1.0,
        "note": "external engine (sqlite3) on identical generated data",
        "q6_rows_per_sec": round(n / q6_s, 1),
    }


def _probe_backend(attempts: int = 3, timeout_s: int = 150):
    """Verify the accelerator backend actually initializes AND completes a
    device round-trip — in a CHILD process, so a hung remote-TPU tunnel
    (jax.devices() can block forever on a dead axon link) cannot hang the
    bench itself.  Returns (platform, None) or (None, diagnostics)."""
    code = ("import jax, numpy as np, jax.numpy as jnp;"
            "d = jax.devices();"
            "v = int(np.asarray(jax.device_put(jnp.arange(8)).sum()));"
            "assert v == 28;"
            "print('PROBE_OK', d[0].platform, len(d))")
    errs = []
    for i in range(attempts):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout_s)
            out = r.stdout.strip().splitlines()
            ok = [ln for ln in out if ln.startswith("PROBE_OK")]
            if r.returncode == 0 and ok:
                return ok[0].split()[1], None
            errs.append(f"rc={r.returncode} "
                        f"{(r.stderr or r.stdout)[-200:]}".strip())
        except subprocess.TimeoutExpired:
            errs.append(f"probe timed out after {timeout_s}s "
                        "(backend init hang)")
        if i + 1 < attempts:
            time.sleep(20)
    return None, "; ".join(errs)[-500:]


def _emit(obj) -> None:
    print(json.dumps(obj), flush=True)


# Documented single-host run-to-run spread, the PR 12/13 way but
# measured wholesale (2026-08: three reruns of one tree on the 1-core
# CI host; the stdlib-sqlite CONTROL config — zero repo code — swung
# -31%/+50% between back-to-back runs, so the spread is host
# scheduling noise, not engine drift).  Bands are the max measured
# spread per config rounded up; perf_regress widens its gate to the
# band for these configs only, so the trajectory still gates the
# trend.  Matched by metric-name fragment (scale prefix varies).
_HOST_NOISE_BANDS = (
    ("cpu_sqlite_", 0.55),
    ("q3_engine_rows_per_sec", 0.55),
    ("concurrent_qps_peak", 0.40),
    ("q1_mesh_2worker_rows_per_sec", 0.35),
    ("q3_join_agg_rows_per_sec_per_chip", 0.30),
    ("q17_join_agg_rows_per_sec_per_chip", 0.30),
    ("sharded_join_rows_per_sec", 0.30),
    ("q1_rows_per_sec_per_chip", 0.25),
    ("q6_rows_per_sec_per_chip", 0.25),
    ("q9_join_agg_rows_per_sec_per_chip", 0.25),
    ("q1_engine_rows_per_sec", 0.25),
)


def _stamp_noise_band(row) -> None:
    m = row.get("metric", "")
    for frag, band in _HOST_NOISE_BANDS:
        if frag in m:
            # never narrow a band a config already declares (spooled
            # tpcds carries 0.6 from its own investigation)
            row["noise_band"] = max(row.get("noise_band", 0.0), band)
            return


def _run_jobs(headline, jobs, budget_s):
    extras = []
    t_start = time.perf_counter()
    for fn, scale, need_frac in jobs:
        elapsed = time.perf_counter() - t_start
        if need_frac and elapsed > budget_s * (1.0 - need_frac):
            extras.append({"metric": f"{fn.__name__}_sf{scale:g}_skipped",
                           "note": f"bench budget ({elapsed:.0f}s used)"})
            continue
        try:
            extras.append(fn(scale))
        except Exception as e:  # noqa: BLE001 - one config must not
            extras.append({"metric": f"{fn.__name__}_sf{scale:g}_failed",
                           "error": str(e)[:200]})
    # anchor the headline ratio externally when the sqlite baseline ran:
    # rows/s at the measured scales (sqlite rows/s is ~scale-invariant)
    for e in extras:
        if e.get("metric", "").startswith("cpu_sqlite") \
                and "value" in e and headline.get("value"):
            headline["vs_external_sqlite"] = round(
                headline["value"] / e["value"], 1)
    if not headline.pop("parity", True):
        headline = {"metric": "tpch_q1_parity_failure", "value": 0.0,
                    "unit": "rows/s", "vs_baseline": 0.0}
    for row in extras:
        _stamp_noise_band(row)
    _stamp_noise_band(headline)
    headline["extras"] = extras
    return headline


def _cpu_fallback_line(probe_err: str) -> dict:
    """The accelerator is unreachable: still emit a machine-readable
    artifact, with a small CPU-backend parity suite as evidence the
    harness itself is sound (rows/s on host CPU is not the headline)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize TPU hook
    env["JAX_PLATFORMS"] = "cpu"
    env["PRESTO_TPU_BENCH_CPU_ONLY"] = "1"
    inner = None
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__),
                            "0.05"], env=env, capture_output=True,
                           text=True, timeout=1800)
        for ln in reversed(r.stdout.strip().splitlines()):
            try:
                inner = json.loads(ln)
                break
            except ValueError:
                continue
    except Exception as e:  # noqa: BLE001
        inner = {"error": str(e)[:200]}
    return {"metric": "bench_backend_unavailable", "value": 0.0,
            "unit": "rows/s", "vs_baseline": 0.0,
            "error": probe_err,
            "note": ("accelerator backend unreachable at capture time; "
                     "cpu_parity_suite ran the same kernels + oracles on "
                     "the CPU backend at small scale"),
            "cpu_parity_suite": inner}


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--sharded-join-inner":
        # subprocess entry for bench_mesh_sharded_join (8 virtual
        # devices forced via XLA_FLAGS by the parent)
        _emit(_sharded_join_inner(float(sys.argv[2])))
        return
    q1_scale = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    budget_s = float(os.environ.get("PRESTO_TPU_BENCH_BUDGET_S", "1500"))
    cpu_only = os.environ.get("PRESTO_TPU_BENCH_CPU_ONLY") == "1"
    if cpu_only:
        # parity-evidence mode (invoked by _cpu_fallback_line or by CI):
        # small scales, every config, on the CPU backend
        import jax

        jax.config.update("jax_platforms", "cpu")
        headline = bench_q1(q1_scale)
        headline["platform"] = "cpu"
        jobs = [(bench_q6, 0.1, 0.0), (bench_q3, 0.1, 0.0),
                (bench_q9, 0.1, 0.0), (bench_q17, 0.1, 0.0),
                (bench_q3_chunked, 0.2, 0.0),
                (bench_engine_q1q6, 0.05, 0.0),
                (bench_engine_q3q9, 0.05, 0.0),
                (bench_mesh_q1q6, 0.05, 0.0),
                (bench_mesh_sharded_join, 0.2, 0.0),
                (bench_tpcds_mesh_q72q95, 0.003, 0.0),
                (bench_tpcds_mesh_q72q95_spooled, 0.003, 0.0),
                (bench_concurrent_qps, 0.003, 0.0),
                (bench_sqlite_baseline, 0.05, 0.0)]
        _emit(_run_jobs(headline, jobs, budget_s))
        return
    platform, probe_err = _probe_backend()
    if platform is None:
        _emit(_cpu_fallback_line(probe_err))
        return
    headline = bench_q1(q1_scale)
    headline["platform"] = platform
    # cheap configs first; the biggest scales run only with budget left.
    # Single-program Q3 tops out at SF30 (the axon remote-compile helper
    # 500s on the 600M-row program); bench_q3_chunked streams SF100 as
    # order-aligned chunk dispatches through one compiled program — the
    # grouped-execution (P9) idea applied to the bench — so the pinned
    # SF100 configs (BASELINE.json) are measured either way.
    jobs = [(bench_q6, 10.0, 0.0), (bench_q3, 1.0, 0.0),
            (bench_q9, 1.0, 0.0), (bench_q17, 1.0, 0.0),
            (bench_engine_q1q6, 1.0, 0.0),
            (bench_engine_q3q9, 0.2, 0.0),
            (bench_mesh_q1q6, 0.2, 0.0),
            (bench_mesh_sharded_join, 1.0, 0.0),
            (bench_tpcds_mesh_q72q95, 0.003, 0.0),
            (bench_tpcds_mesh_q72q95_spooled, 0.003, 0.0),
            (bench_concurrent_qps, 0.003, 0.0),
            (bench_whole_query_q3, 0.1, 0.0),
            (bench_sqlite_baseline, 0.2, 0.0),
            (bench_q3, 10.0, 0.65),
            (bench_q9, 10.0, 0.55), (bench_q17, 10.0, 0.5),
            (bench_q3, 30.0, 0.4),
            (bench_q3_chunked, 100.0, 0.3),
            (bench_q9, 100.0, 0.2), (bench_q17, 100.0, 0.15)]
    _emit(_run_jobs(headline, jobs, budget_s))


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 - the artifact must stay
        # machine-readable even on a crash (VERDICT r4 weak #1)
        _emit({"metric": "bench_crashed", "value": 0.0, "unit": "rows/s",
               "vs_baseline": 0.0,
               "error": f"{type(e).__name__}: {e}"[:300]})
        sys.exit(0)
