"""Benchmarks: the BASELINE.md pinned configs on one TPU chip.

Three hand-built device pipelines (the presto-benchmark suite pattern —
hand-assembled operator pipelines, AbstractOperatorBenchmark.java:97,
HandTpchQuery1.java / HandTpchQuery6.java / HashBuildAndJoinBenchmark):

1. TPC-H SF1 Q1  — scan + grouped aggregation (headline metric)
2. TPC-H SF10 Q6 — predicate + projection + global aggregation
3. TPC-H SF1 Q3 core — 3-way join + aggregation + TopN, exploiting
   TPC-H's dense integer keys TPU-first: FK joins become boolean-table
   gathers, the revenue aggregation is a scatter-add over the dense
   orderkey domain, TopN is lax.top_k — no sorts, so the program is both
   compile-cheap and HBM-bound (the reference's HashBuilder/LookupJoin
   for the same query walks hash tables row-at-a-time).

Each config reports rows/s and effective input bytes/s, with parity
against a vectorized-numpy CPU implementation (the stand-in for the
reference's CPU operator pipeline — its codegen also reduces to tight
CPU loops over columnar arrays; the reference publishes no absolute
numbers, BASELINE.md).

Timing methodology (axon tunnel quirks): run K dependence-chained
iterations INSIDE one jitted fori_loop and take the slope between two K
values, so RPC overhead and sync-polling granularity cancel.

Prints exactly ONE JSON line; the headline is Q1 and the other configs
ride in "extras":
    {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N,
     "extras": [...]}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

Q6_DATE_LO, Q6_DATE_HI = 8766, 9131          # 1994-01-01 .. 1995-01-01
# BETWEEN 0.05 AND 0.07 via class midpoints: the generated discounts are
# the 11 cent classes 0.00..0.10, and the 0.05/0.07 boundaries sit on
# float knife-edges that f32-physical device doubles and host f64 round
# differently; midpoint thresholds select exactly {0.05,0.06,0.07} under
# either precision
Q6_DISC_LO, Q6_DISC_HI = 0.045, 0.075
Q3_DATE = 9204                               # 1995-03-15, epoch days


def _slope_time(make_chained, args) -> float:
    """Seconds per iteration via the two-K dependence-chained slope."""
    f5 = make_chained(5)
    np.asarray(f5(args))
    t0 = time.perf_counter()
    np.asarray(f5(args))
    rough = max((time.perf_counter() - t0) / 5, 1e-5)
    k1 = 3
    k2 = k1 + max(20, min(2000, int(4.0 / rough)))
    ts = []
    for k in (k1, k2):
        f = make_chained(k)
        np.asarray(f(args))  # compile + warm (sync via host read)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(f(args))
            best = min(best, time.perf_counter() - t0)
        ts.append(best)
    return max((ts[1] - ts[0]) / (k2 - k1), 1e-9)


def _col_bytes(arrays) -> int:
    return int(sum(np.asarray(a).nbytes if not hasattr(a, "nbytes")
                   else a.nbytes for a in arrays))


# ---------------------------------------------------------------------------
# Config 1: TPC-H Q1 (scan + grouped aggregation)
# ---------------------------------------------------------------------------

def _cpu_q1(rf, ls, qty, price, disc, tax, shipdate, n):
    sel = shipdate[:n] <= 10471
    rf, ls = rf[:n][sel], ls[:n][sel]
    qty, price = qty[:n][sel], price[:n][sel]
    disc, tax = disc[:n][sel], tax[:n][sel]
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)
    key = rf.astype(np.int64) * 64 + ls
    uniq, inv = np.unique(key, return_inverse=True)
    out = []
    for col in (qty, price, disc_price, charge, disc):
        out.append(np.bincount(inv, weights=col, minlength=len(uniq)))
    out.append(np.bincount(inv, minlength=len(uniq)))
    return uniq, out


def bench_q1(scale: float):
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _q1_arrays, q1_step

    args = _q1_arrays(scale)

    def chained(k):
        def body(_, carry):
            a, acc = carry
            out = q1_step(*a[:2], a[2] + (acc - acc).astype(a[2].dtype),
                          *a[3:])
            return (a, acc + out[3][0])
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    device_s = _slope_time(chained, args)
    n = int(args[-1])

    out = jax.jit(q1_step)(*args)
    host = [np.asarray(a) for a in args[:-1]]
    t0 = time.perf_counter()
    cpu = _cpu_q1(*host, n)
    cpu_s = time.perf_counter() - t0

    ng = int(out[2])
    dev_key = (np.asarray(out[0])[:ng].astype(np.int64) * 64
               + np.asarray(out[1])[:ng])
    order = np.argsort(dev_key)
    ok = bool(np.array_equal(dev_key[order], cpu[0]))
    for i, want in enumerate(cpu[1]):
        got = np.asarray(out[3 + i])[:ng][order]
        ok = ok and bool(np.allclose(got, want, rtol=1e-6))
    nbytes = _col_bytes(host) * n // max(host[0].shape[0], 1)
    return {
        "metric": f"tpch_sf{scale:g}_q1_rows_per_sec_per_chip",
        "value": round(n / device_s, 1), "unit": "rows/s",
        "vs_baseline": round(n / device_s / (n / cpu_s), 3),
        "bytes_per_sec": round(nbytes / device_s, 1),
        "parity": ok,
    }


# ---------------------------------------------------------------------------
# Config 2: TPC-H Q6 (filter + projection + global sum)
# ---------------------------------------------------------------------------

def _q6_arrays(scale: float):
    import jax.numpy as jnp

    from presto_tpu.batch import concat_batches, next_bucket
    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=scale)
    handle = conn.get_table("lineitem")
    cols = ["l_shipdate", "l_discount", "l_quantity", "l_extendedprice"]
    batches = []
    for split in conn.get_splits(handle, 1):
        batches.extend(conn.page_source(split, cols, 1 << 24))
    b = concat_batches(batches) if len(batches) > 1 else batches[0]
    cap = next_bucket(b.num_rows)
    b = b.pad_rows(cap)
    arrays = tuple(jnp.asarray(c.values) for c in b.columns)
    return arrays + (jnp.asarray(b.num_rows, jnp.int64),)


def q6_step(shipdate, disc, qty, price, num_rows):
    """WHERE l_shipdate in [1994, 1995) AND l_discount BETWEEN 0.05 AND
    0.07 AND l_quantity < 24 -> SUM(l_extendedprice * l_discount), fused
    into the aggregation as a live mask (HandTpchQuery6 role)."""
    import jax.numpy as jnp

    live = jnp.arange(shipdate.shape[0]) < num_rows
    sel = (live & (shipdate >= Q6_DATE_LO) & (shipdate < Q6_DATE_HI)
           & (disc > Q6_DISC_LO) & (disc < Q6_DISC_HI) & (qty < 24.0))
    return jnp.where(sel, price * disc, 0.0).sum()


def _cpu_q6(shipdate, disc, qty, price, n):
    sel = ((shipdate[:n] >= Q6_DATE_LO) & (shipdate[:n] < Q6_DATE_HI)
           & (disc[:n] > Q6_DISC_LO) & (disc[:n] < Q6_DISC_HI)
           & (qty[:n] < 24.0))
    return float((price[:n][sel] * disc[:n][sel]).sum())


def bench_q6(scale: float):
    import jax
    import jax.numpy as jnp

    args = _q6_arrays(scale)

    def chained(k):
        def body(_, carry):
            a, acc = carry
            s = q6_step(a[0] + (acc - acc).astype(a[0].dtype), *a[1:])
            return (a, acc + s)
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    device_s = _slope_time(chained, args)
    n = int(args[-1])
    host = [np.asarray(a) for a in args[:-1]]
    t0 = time.perf_counter()
    want = _cpu_q6(*host, n)
    cpu_s = time.perf_counter() - t0
    got = float(jax.jit(q6_step)(*args))
    ok = bool(np.isclose(got, want, rtol=1e-6))
    nbytes = _col_bytes(host) * n // max(host[0].shape[0], 1)
    return {
        "metric": f"tpch_sf{scale:g}_q6_rows_per_sec_per_chip",
        "value": round(n / device_s, 1), "unit": "rows/s",
        "vs_baseline": round(n / device_s / (n / cpu_s), 3),
        "bytes_per_sec": round(nbytes / device_s, 1),
        "parity": ok,
    }


# ---------------------------------------------------------------------------
# Config 3: TPC-H Q3 core (3-way join + aggregation + TopN)
# ---------------------------------------------------------------------------

def _q3_arrays(scale: float):
    import jax.numpy as jnp

    from presto_tpu.batch import concat_batches, next_bucket
    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=scale)

    def load(table, cols):
        h = conn.get_table(table)
        batches = []
        for split in conn.get_splits(h, 1):
            batches.extend(conn.page_source(split, cols, 1 << 24))
        return concat_batches(batches) if len(batches) > 1 else batches[0]

    cust = load("customer", ["c_custkey", "c_mktsegment"])
    seg = cust.columns[1]
    building_code = seg.dictionary.code_of("BUILDING")
    n_cust = cust.num_rows
    # dense boolean membership table over the custkey domain (keys are
    # 1..N in order): the build side of join #1, as one gather table
    cust_building = np.zeros(n_cust + 1, bool)
    cust_building[np.asarray(cust.columns[0].values)[:n_cust]] = (
        np.asarray(seg.values)[:n_cust] == building_code)

    orders = load("orders", ["o_orderkey", "o_custkey", "o_orderdate"])
    n_ord = orders.num_rows
    ocust = np.asarray(orders.columns[1].values)[:n_ord]
    odate = np.asarray(orders.columns[2].values)[:n_ord]

    li = load("lineitem", ["l_orderkey", "l_extendedprice", "l_discount",
                           "l_shipdate"])
    n_li = li.num_rows
    cap = next_bucket(n_li)
    li = li.pad_rows(cap)
    # f32/i32 on device: the v5e stores "f64" as f32 anyway (X64 rewrite)
    # and emulated 64-bit elementwise ops would dominate the runtime;
    # per-order revenue sums at most 7 f32 terms so precision holds
    okey0 = np.clip(np.asarray(li.columns[0].values) - 1, 0,
                    n_ord - 1).astype(np.int32)
    arrays = (
        jnp.asarray(cust_building),
        jnp.asarray(ocust.astype(np.int32)),
        jnp.asarray(odate.astype(np.int32)),
        jnp.asarray(okey0),
        jnp.asarray(np.asarray(li.columns[1].values,
                               dtype=np.float32)),
        jnp.asarray(np.asarray(li.columns[2].values,
                               dtype=np.float32)),
        jnp.asarray(np.asarray(li.columns[3].values, dtype=np.int32)),
        jnp.asarray(n_li, jnp.int64),
    )
    rows = n_cust + n_ord + n_li
    # 4 lineitem device arrays (okey0/price/disc/ship, 4B each)
    nbytes = (cust_building.nbytes + 2 * 4 * n_ord + 4 * 4 * n_li)
    # keep f64 copies for the CPU oracle
    host = (cust_building, ocust, odate,
            np.asarray(li.columns[0].values)[:n_li],
            np.asarray(li.columns[1].values)[:n_li],
            np.asarray(li.columns[2].values)[:n_li],
            np.asarray(li.columns[3].values)[:n_li], n_li)
    return arrays, host, rows, nbytes


def q3_step(cust_building, ocust, odate, okey0, price, disc, ship, n_li):
    """Q3's join+agg+TopN core as one XLA program over dense keys:

        sel_orders = building[o_custkey] & o_orderdate < DATE   (join #1
                     + filter: a gather and a compare)
        sel_line   = sel_orders[l_orderkey] & l_shipdate > DATE (join #2)
        revenue    = 7-tap same-key windowed sum at each order's last
                     lineitem (orders have <= 7 adjacent lineitems, so
                     no scatter and no sort)
        top 10 revenue via blocked two-stage lax.top_k

    The reference executes this as HashBuilder/LookupJoin x2 +
    HashAggregation + TopN (presto-main/.../operator/, SURVEY §3.4);
    dense TPC-H keys let the TPU do it bandwidth-bound with no hash
    table."""
    import jax
    import jax.numpy as jnp

    sel_ord = cust_building[ocust] & (odate < Q3_DATE)
    live = jnp.arange(okey0.shape[0]) < n_li
    sel_li = live & (ship > Q3_DATE) & sel_ord[okey0]
    contrib = jnp.where(sel_li, price * (1.0 - disc), jnp.float32(0))
    rev = contrib
    for j in range(1, 7):
        shifted = jnp.concatenate(
            [jnp.zeros(j, contrib.dtype), contrib[:-j]])
        same = jnp.concatenate(
            [jnp.zeros(j, bool), okey0[j:] == okey0[:-j]])
        rev = rev + jnp.where(same, shifted, 0)
    end = jnp.concatenate([okey0[1:] != okey0[:-1], jnp.ones(1, bool)])
    rev = jnp.where(end & live, rev, jnp.float32(-1.0))
    B = 1024
    pad = (-rev.shape[0]) % B
    r2 = jnp.pad(rev, (0, pad), constant_values=-1.0).reshape(B, -1)
    tv, ti = jax.lax.top_k(r2, 10)
    base = (jnp.arange(B) * r2.shape[1])[:, None]
    cv, ci = jax.lax.top_k(tv.reshape(-1), 10)
    pos = (base + ti).reshape(-1)[ci]
    return cv, okey0[jnp.clip(pos, 0, okey0.shape[0] - 1)] + 1


def _cpu_q3(cust_building, ocust, odate, l_okey, l_price, l_disc,
            l_ship, n_li):
    sel_ord = cust_building[ocust] & (odate < Q3_DATE)
    okey0 = l_okey[:n_li] - 1
    sel_li = (l_ship[:n_li] > Q3_DATE) & sel_ord[okey0]
    contrib = np.where(sel_li, l_price[:n_li] * (1.0 - l_disc[:n_li]), 0.0)
    rev = np.bincount(okey0, weights=contrib, minlength=len(ocust))
    top = np.argsort(-rev, kind="stable")[:10]
    return rev[top]


def bench_q3(scale: float):
    import jax
    import jax.numpy as jnp

    args, host, rows, nbytes = _q3_arrays(scale)

    def chained(k):
        def body(_, carry):
            a, acc = carry
            out = q3_step(a[0], a[1], a[2],
                          a[3] + (acc - acc).astype(a[3].dtype), *a[4:])
            return (a, acc + out[0][0].astype(jnp.float64))
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    device_s = _slope_time(chained, args)

    t0 = time.perf_counter()
    want = _cpu_q3(*host)
    cpu_s = time.perf_counter() - t0
    got = np.sort(np.asarray(jax.jit(q3_step)(*args)[0]))[::-1]
    # f32 revenue sums: ~1e-5 relative (SQL float aggregation order is
    # unspecified; the reference reorders too)
    ok = bool(np.allclose(got, np.sort(want)[::-1], rtol=1e-4))
    return {
        "metric": f"tpch_sf{scale:g}_q3_join_agg_rows_per_sec_per_chip",
        "value": round(rows / device_s, 1), "unit": "rows/s",
        "vs_baseline": round(rows / device_s / (rows / cpu_s), 3),
        "bytes_per_sec": round(nbytes / device_s, 1),
        "parity": ok,
    }


def bench_whole_query_q3(scale: float):
    """The generic one-XLA-program tier (parallel/sqlmesh) on TPC-H Q3
    text — the flagship mode's warm wall clock (cold compile amortized
    by the persistent XLA cache)."""
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.parallel.sqlmesh import MeshQueryRunner

    sql = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""
    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=scale))
    r = MeshQueryRunner(reg, "tpch", n_devices=1)
    r.execute(sql)                         # compile + warm
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = r.execute(sql)
        walls.append(time.perf_counter() - t0)
    return {
        "metric": f"tpch_sf{scale:g}_q3_whole_query_warm_wall_s",
        "value": round(min(walls), 3), "unit": "s",
        "vs_baseline": 0.0,
        "note": ("generic SPMD lowering, one program; includes the "
                 "remote-TPU tunnel's per-dispatch latency"),
        "rows": len(res.rows),
    }


def bench_sqlite_baseline(scale: float):
    """External (non-self-authored) CPU baseline: the sqlite3 engine over
    IDENTICAL generated data, per BASELINE.md's measurement note — the
    'reference CPU engine' stand-in the builder did not write."""
    import sqlite3

    from presto_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(scale=scale)
    db = sqlite3.connect(":memory:")
    for table, cols in (
        ("lineitem", ["l_orderkey", "l_quantity", "l_extendedprice",
                      "l_discount", "l_tax", "l_returnflag",
                      "l_linestatus", "l_shipdate"]),
    ):
        h = conn.get_table(table)
        schema = conn.table_schema(h)
        db.execute(f"create table {table} ("
                   + ", ".join(f"{c} NUMERIC" for c in cols) + ")")
        n = 0
        for split in conn.get_splits(h, 1):
            for b in conn.page_source(split, cols, 1 << 20):
                rows = b.to_pylist()
                db.executemany(
                    f"insert into {table} values "
                    f"({', '.join('?' * len(cols))})",
                    [[str(v) if not isinstance(v, (int, float)) else v
                      for v in r] for r in rows])
                n += b.num_rows
        db.commit()
    t0 = time.perf_counter()
    db.execute(
        "select l_returnflag, l_linestatus, sum(l_quantity), "
        "sum(l_extendedprice), sum(l_extendedprice*(1-l_discount)), "
        "sum(l_extendedprice*(1-l_discount)*(1+l_tax)), sum(l_discount), "
        "count(*) from lineitem where l_shipdate <= 10471 "
        "group by l_returnflag, l_linestatus").fetchall()
    q1_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    db.execute(
        "select sum(l_extendedprice*l_discount) from lineitem "
        f"where l_shipdate >= {Q6_DATE_LO} and l_shipdate < {Q6_DATE_HI} "
        f"and l_discount > {Q6_DISC_LO} and l_discount < {Q6_DISC_HI} "
        "and l_quantity < 24").fetchall()
    q6_s = time.perf_counter() - t0
    db.close()
    return {
        "metric": f"cpu_sqlite_sf{scale:g}_q1_rows_per_sec",
        "value": round(n / q1_s, 1), "unit": "rows/s",
        "vs_baseline": 1.0,
        "note": "external engine (sqlite3) on identical generated data",
        "q6_rows_per_sec": round(n / q6_s, 1),
    }


def main() -> None:
    q1_scale = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    headline = bench_q1(q1_scale)
    extras = []
    t_start = time.perf_counter()
    budget_s = float(os.environ.get("PRESTO_TPU_BENCH_BUDGET_S", "1500"))
    # cheap configs first; the SF100 north-star (config 3) runs only with
    # budget left — its host generation + 10GB tunnel transfer is minutes
    # SF100 Q3 (config 3's stated scale) exceeds the axon tunnel's
    # remote-compile helper (HTTP 500 at the 600M-row program); SF30 is
    # the largest join+agg scale the tunnel toolchain accepts — the
    # single-chip HBM ceiling itself is ~SF120 for the Q3 working set
    # (see BASELINE.md)
    jobs = [(bench_q6, 10.0, 0.0), (bench_q3, 1.0, 0.0),
            (bench_whole_query_q3, 0.1, 0.0),
            (bench_sqlite_baseline, 0.2, 0.0),
            (bench_q3, 10.0, 0.55), (bench_q3, 30.0, 0.35)]
    for fn, scale, need_frac in jobs:
        elapsed = time.perf_counter() - t_start
        if need_frac and elapsed > budget_s * (1.0 - need_frac):
            extras.append({"metric": f"{fn.__name__}_sf{scale:g}_skipped",
                           "note": f"bench budget ({elapsed:.0f}s used)"})
            continue
        try:
            extras.append(fn(scale))
        except Exception as e:  # noqa: BLE001 - one config must not
            extras.append({"metric": f"{fn.__name__}_sf{scale:g}_failed",
                           "error": str(e)[:200]})
    # anchor the headline ratio externally when the sqlite baseline ran:
    # rows/s at the measured scales (sqlite rows/s is ~scale-invariant)
    for e in extras:
        if e.get("metric", "").startswith("cpu_sqlite") \
                and "value" in e and headline.get("value"):
            headline["vs_external_sqlite"] = round(
                headline["value"] / e["value"], 1)
    if not headline.pop("parity", True):
        headline = {"metric": "tpch_q1_parity_failure", "value": 0.0,
                    "unit": "rows/s", "vs_baseline": 0.0}
    headline["extras"] = extras
    print(json.dumps(headline))


if __name__ == "__main__":
    main()
