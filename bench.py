"""Benchmark: TPC-H SF1 Q1 rows/sec/chip through the fused TPU pipeline.

Pinned config #1 of BASELINE.md (single-table scan + grouped aggregation,
the reference's HandTpchQuery1 / HashAggregationOperator path,
presto-benchmark/.../HandTpchQuery1.java).  The reference publishes no
absolute numbers (BASELINE.md), so ``vs_baseline`` compares the device
kernel against a measured vectorized-numpy CPU implementation of the same
query on this host — a stand-in for the reference's CPU operator pipeline
(its Java codegen also reduces to tight CPU loops over columnar arrays).

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "rows/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _cpu_q1(rf, ls, qty, price, disc, tax, shipdate, n):
    """Vectorized numpy Q1 (the CPU-engine stand-in baseline)."""
    sel = shipdate[:n] <= 10471
    rf, ls = rf[:n][sel], ls[:n][sel]
    qty, price = qty[:n][sel], price[:n][sel]
    disc, tax = disc[:n][sel], tax[:n][sel]
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)
    key = rf.astype(np.int64) * 64 + ls
    uniq, inv = np.unique(key, return_inverse=True)
    out = []
    for col in (qty, price, disc_price, charge, disc):
        out.append(np.bincount(inv, weights=col, minlength=len(uniq)))
    out.append(np.bincount(inv, minlength=len(uniq)))
    return uniq, out


def main() -> None:
    import jax

    from __graft_entry__ import _q1_arrays, q1_step

    scale = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    args = _q1_arrays(scale)

    # Timing methodology (axon quirks, see memory/verify notes): (a) a
    # device->host read switches the process into ~1s-per-call sync
    # polling, and (b) block_until_ready under-reports on the tunnel.  So:
    # run K dependence-chained iterations INSIDE one jitted fori_loop,
    # materialize one scalar, and take the slope between two K values —
    # RPC overhead and polling granularity cancel out.
    import jax.numpy as jnp

    def chained(k):
        def body(_, carry):
            a, acc = carry
            out = q1_step(*a[:2], a[2] + (acc - acc).astype(a[2].dtype),
                          *a[3:])
            return (a, acc + out[3][0])
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, k, body, (a, jnp.float64(0.0)))[1])

    # calibrate so the k-spread contributes >> RPC jitter (~100ms)
    f5 = chained(5)
    np.asarray(f5(args))
    t0 = time.perf_counter()
    np.asarray(f5(args))
    rough = max((time.perf_counter() - t0) / 5, 1e-5)
    k1 = 3
    k2 = k1 + max(20, min(2000, int(4.0 / rough)))
    ts = []
    for k in (k1, k2):
        f = chained(k)
        np.asarray(f(args))  # compile + warm (sync via host read)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(f(args))
            best = min(best, time.perf_counter() - t0)
        ts.append(best)
    device_s = max((ts[1] - ts[0]) / (k2 - k1), 1e-9)
    n = int(args[-1])
    rows_per_sec = n / device_s

    jitted = jax.jit(q1_step)
    out = jitted(*args)

    host = [np.asarray(a) for a in args[:-1]]
    t0 = time.perf_counter()
    cpu = _cpu_q1(*host, n)
    cpu_s = time.perf_counter() - t0

    # parity check: device sums must match the CPU oracle
    ng = int(out[2])
    dev_key = (np.asarray(out[0])[:ng].astype(np.int64) * 64
               + np.asarray(out[1])[:ng])
    order = np.argsort(dev_key)
    ok = bool(np.array_equal(dev_key[order], cpu[0]))
    for i, want in enumerate(cpu[1]):
        got = np.asarray(out[3 + i])[:ng][order]
        # MXU hi/lo-split sums carry ~1e-9 rel error (SQL float aggregation
        # has no bit-exact ordering guarantee; the reference reorders too)
        ok = ok and bool(np.allclose(got, want, rtol=1e-6))
    if not ok:
        print(json.dumps({"metric": "tpch_q1_parity_failure", "value": 0.0,
                          "unit": "rows/s", "vs_baseline": 0.0}))
        return

    print(json.dumps({
        "metric": f"tpch_sf{scale:g}_q1_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round((n / cpu_s) and rows_per_sec / (n / cpu_s), 3),
    }))


if __name__ == "__main__":
    main()
