"""Shared plan cache: repeated statements skip parse/analyze/optimize.

Role model: the reference plans every statement from scratch but caches
every *generated class* (ExpressionCompiler etc.); serving-tier forks
(and the reference's own ``EXECUTE`` path) add a query-plan cache so a
dashboard firing the same statement hundreds of times per minute pays
the semantic-analysis + cost-based-optimization price once.  This module
is that cache for both tiers:

- the **coordinator** (server/dispatcher.py ``DispatchQuery``) caches
  the fragmented ``DistributedPlan`` + output schema + plan text;
- the **local runner** caches the optimized logical plan.

Keys and invalidation
---------------------
An entry is keyed on ``(epoch-domain token, catalog, schema,
session-property fingerprint, normalized SQL text)``:

- *normalized SQL*: whitespace collapsed outside string literals, so
  formatting differences between clients share one entry;
- *session-property fingerprint*: any property change (planner knobs,
  fusion toggles...) produces a different plan — different key;
- *epoch-domain token*: a unique id per ``StatsEpochs`` domain (one per
  ``ConnectorRegistry``), so two clusters in one process never share
  entries.

Invalidation is by **per-catalog stats epochs** (the reference's
stats-based CBO makes plans a function of table statistics): every
DDL/DML that changes data or metadata in a catalog bumps that catalog's
epoch, and an entry records the epoch of every catalog its plan scans at
insert time.  A lookup whose recorded epochs no longer match is a miss
(the stale entry is dropped and counted as an eviction).

The cache itself is a named ``kernelcache.KernelCache`` ("plan_cache"),
so hit/miss/eviction counters surface through the same registry as the
compiled-kernel caches (task info, EXPLAIN ANALYZE, /metrics).
"""

from __future__ import annotations

import dataclasses
import threading
import uuid
from typing import Any, Dict, Iterable, Optional, Tuple

from presto_tpu import kernelcache

# One process-wide cache (coordinator-lifetime by construction, like the
# compiled-kernel caches); the epoch-domain token in every key isolates
# independent registries sharing the process.
_CACHE = kernelcache.new_cache("plan_cache")


class StatsEpochs:
    """Per-catalog statistics epochs for one connector registry.

    ``bump(catalog)`` after any statement that changes the catalog's
    data or metadata (INSERT/DELETE/CTAS/DDL/ANALYZE/view changes);
    cached plans referencing that catalog stop validating.  Thread-safe;
    epochs only grow."""

    def __init__(self):
        self.token = uuid.uuid4().hex[:12]
        self._lock = threading.Lock()
        self._epochs: Dict[str, int] = {}

    def epoch(self, catalog: str) -> int:
        with self._lock:
            return self._epochs.get(catalog, 0)

    def bump(self, catalog: str) -> int:
        with self._lock:
            self._epochs[catalog] = self._epochs.get(catalog, 0) + 1
            return self._epochs[catalog]

    def snapshot(self, catalogs: Iterable[str]) -> Dict[str, int]:
        with self._lock:
            return {c: self._epochs.get(c, 0) for c in catalogs}

    def valid(self, snapshot: Dict[str, int]) -> bool:
        with self._lock:
            return all(self._epochs.get(c, 0) == e
                       for c, e in snapshot.items())


def epochs_for(registry) -> StatsEpochs:
    """The StatsEpochs domain of a ConnectorRegistry (created on first
    use and attached, so the coordinator and its embedded utility
    runners — which share the registry — share one epoch space)."""
    ep = getattr(registry, "_stats_epochs", None)
    if ep is None:
        ep = StatsEpochs()
        registry._stats_epochs = ep
    return ep


def normalize_sql(sql: str) -> str:
    """Collapse whitespace runs outside single-quoted string literals
    and strip a trailing semicolon, so trivially-reformatted statements
    share one cache entry.  Case is preserved (identifiers may be
    delimited; string literals are significant)."""
    out = []
    in_string = False
    pending_space = False
    for ch in sql:
        if in_string:
            out.append(ch)
            if ch == "'":
                in_string = False
            continue
        if ch == "'":
            if pending_space and out:
                out.append(" ")
            pending_space = False
            out.append(ch)
            in_string = True
            continue
        if ch.isspace():
            pending_space = True
            continue
        if pending_space and out:
            out.append(" ")
        pending_space = False
        out.append(ch)
    text = "".join(out)
    return text[:-1].rstrip() if text.endswith(";") else text


#: the non-deterministic scalar families (FunctionRegistry's
#: ``isDeterministic=false`` role): two executions of a statement
#: containing any of these legitimately differ, so a RESULT over them
#: must never be replayed from a cache.  Plans over them stay cacheable
#: (the plan is deterministic; its rows are not) — this predicate gates
#: result-cache admission only, sharing the plan cache's normalization.
NONDETERMINISTIC_FUNCTIONS = frozenset({
    "now", "current_timestamp", "current_date", "current_time",
    "localtimestamp", "localtime", "random", "rand", "uuid",
    "shuffle", "unix_timestamp",
})

_NONDET_RE = None


def _strip_string_literals(sql: str) -> str:
    out = []
    in_string = False
    for ch in sql:
        if in_string:
            if ch == "'":
                in_string = False
            continue
        if ch == "'":
            in_string = True
            continue
        out.append(ch)
    return "".join(out)


def has_nondeterministic_functions(sql: str) -> bool:
    """True when the statement references a non-deterministic scalar
    (``now()``/``current_timestamp``/``random()``-family).  Analyzer-side
    admission predicate for the result cache (server/resultcache.py):
    such a statement must RE-EXECUTE on every repeat.  Matches
    word-boundary identifiers outside string literals; a same-named
    column is a (safe) false positive — it only disables caching."""
    global _NONDET_RE
    if _NONDET_RE is None:
        import re

        _NONDET_RE = re.compile(
            r"\b(" + "|".join(sorted(NONDETERMINISTIC_FUNCTIONS))
            + r")\b", re.IGNORECASE)
    return _NONDET_RE.search(_strip_string_literals(sql)) is not None


def fingerprint(session_properties: Optional[Dict[str, Any]]) -> Tuple:
    """Order-independent session-property fingerprint."""
    return tuple(sorted((str(k), str(v))
                        for k, v in (session_properties or {}).items()))


def cache_key(epochs: StatsEpochs, sql: str, catalog: Optional[str],
              schema: Optional[str],
              session_properties: Optional[Dict[str, Any]] = None) -> Tuple:
    return (epochs.token, catalog or "", schema or "",
            fingerprint(session_properties), normalize_sql(sql))


@dataclasses.dataclass
class _Entry:
    value: Any
    epoch_snapshot: Dict[str, int]


@dataclasses.dataclass
class CachedLocalPlan:
    """LocalQueryRunner cache entry: the optimized logical plan plus —
    filled by the first execution — the physical-planner output, so a
    repeat statement skips BOTH optimize AND the per-execution physical
    re-plan (ROADMAP #3 named it the biggest per-query CPU line item).
    ``in_use`` guards the factories' shared runtime state: a concurrent
    execution of the same statement re-plans privately instead of
    sharing mid-flight factories."""

    optimized: Any
    label: str
    physical: Any = None
    in_use: bool = False


def scan_catalogs(node) -> set:
    """Catalogs referenced by a plan's table scans (the entry's
    invalidation scope)."""
    from presto_tpu.sql.plan import TableScanNode

    out: set = set()

    def walk(n):
        if isinstance(n, TableScanNode):
            out.add(n.catalog)
        for s in n.sources:
            walk(s)

    walk(node)
    return out


def get(key: Tuple, epochs: StatsEpochs):
    """Cached plan value, or None.  A hit whose recorded catalog epochs
    no longer match current epochs is dropped (counted as an eviction)
    and reported as a miss — the DDL/INSERT invalidation path."""
    entry = kernelcache.cache_get(_CACHE, key)
    if entry is None:
        return None
    if not epochs.valid(entry.epoch_snapshot):
        with kernelcache._LOCK:
            if _CACHE.get(key) is entry:
                del _CACHE[key]
                _CACHE.evictions += 1
            # the stale entry was counted as a hit by cache_get; it is
            # a miss for the caller — rebalance the counters
            _CACHE.hits -= 1
            _CACHE.misses += 1
        return None
    return entry.value


def put(key: Tuple, value: Any, epochs: StatsEpochs,
        catalogs: Iterable[str], capacity: Optional[int] = None) -> None:
    entry = _Entry(value, epochs.snapshot(catalogs))
    kernelcache.cache_put(_CACHE, key, entry,
                          cap=capacity if capacity and capacity > 0
                          else None)


def stats() -> Dict[str, int]:
    """Hit/miss/eviction/size counters (the /metrics + bench surface)."""
    with kernelcache._LOCK:
        return {"size": len(_CACHE), "hits": _CACHE.hits,
                "misses": _CACHE.misses, "evictions": _CACHE.evictions}


def clear() -> None:
    """Drop every entry and zero the counters (test isolation)."""
    with kernelcache._LOCK:
        _CACHE.clear()
        _CACHE.hits = _CACHE.misses = _CACHE.evictions = 0
