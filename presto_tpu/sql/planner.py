"""Analyzer + logical planner: AST -> PlanNode tree.

Combines the roles of the reference's StatementAnalyzer (scopes, name
resolution, type checking — presto-main/.../sql/analyzer/StatementAnalyzer
.java:243), SqlToRowExpressionTranslator (sql/relational/
SqlToRowExpressionTranslator.java:122) and LogicalPlanner/QueryPlanner/
SubqueryPlanner (sql/planner/LogicalPlanner.java:176, QueryPlanner.java:97,
SubqueryPlanner.java:71) into one bottom-up pass.  Subquery handling
mirrors the reference's decorrelation rules: uncorrelated IN -> semi join,
correlated EXISTS -> semi/anti join on the correlation equalities (residual
kept on the join), correlated scalar aggregate -> group-by on the
correlation keys + inner join (TransformCorrelated* rules).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.connectors.api import Connector, ConnectorRegistry
from presto_tpu.expr import build as B
from presto_tpu.expr import functions as F
from presto_tpu.expr.functions import (
    FunctionError, resolve_aggregate, resolve_scalar,
)
from presto_tpu.expr.ir import Call, Constant, InputRef, RowExpression, SpecialForm
from presto_tpu.sql import tree as t
from presto_tpu.sql.plan import (
    AggregationNode, EnforceSingleRowNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanAggregate, PlanNode, PlanWindowFunction, ProjectNode,
    SemiJoinNode, SortNode, TableScanNode, UnionNode, ValuesNode, WindowNode,
)

AGG_NAMES = {"count", "sum", "avg", "min", "max", "stddev", "stddev_samp",
             "stddev_pop", "variance", "var_samp", "var_pop", "any_value",
             "arbitrary", "bool_and", "bool_or", "every", "count_if",
             "array_agg", "map_agg", "min_by", "max_by", "approx_distinct",
             "approx_percentile", "corr", "covar_samp", "covar_pop",
             "regr_slope", "regr_intercept", "geometric_mean", "checksum",
             "learn_classifier", "learn_regressor"}


class SqlAnalysisError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    qualifier: Optional[str]
    type: T.Type


class Scope:
    def __init__(self, fields: Sequence[Field],
                 parent: Optional["Scope"] = None):
        self.fields = list(fields)
        self.parent = parent

    def try_resolve(self, parts: Tuple[str, ...]) -> Optional[int]:
        """Channel index in THIS scope only (no parent chain)."""
        if len(parts) == 1:
            hits = [i for i, f in enumerate(self.fields)
                    if f.name == parts[0]]
        elif len(parts) == 2:
            hits = [i for i, f in enumerate(self.fields)
                    if f.name == parts[1] and f.qualifier == parts[0]]
        else:
            return None
        if len(hits) > 1:
            raise SqlAnalysisError(f"column {'.'.join(parts)} is ambiguous")
        return hits[0] if hits else None

    def resolves_locally(self, expr: t.Expression) -> Optional[bool]:
        """True if every identifier in expr resolves here, False if every
        one resolves only in the parent chain, None if mixed/unresolved."""
        local = outer = 0
        for ident in _identifiers(expr):
            if self.try_resolve(ident.parts) is not None:
                local += 1
            elif self.parent is not None and _chain_resolves(self.parent,
                                                            ident.parts):
                outer += 1
            else:
                raise SqlAnalysisError(
                    f"column {ident} cannot be resolved")
        if outer == 0:
            return True
        if local == 0:
            return False
        return None


def _chain_resolves(scope: Scope, parts) -> bool:
    s: Optional[Scope] = scope
    while s is not None:
        if s.try_resolve(parts) is not None:
            return True
        s = s.parent
    return False


def _identifiers(expr: t.Node):
    """All Identifier leaves (not descending into subqueries)."""
    if isinstance(expr, t.Identifier):
        yield expr
        return
    if isinstance(expr, (t.InSubquery, t.Exists, t.ScalarSubquery)):
        if isinstance(expr, t.InSubquery):
            yield from _identifiers(expr.expr)
        return
    for f in getattr(expr, "__dataclass_fields__", {}):
        v = getattr(expr, f)
        if isinstance(v, t.Node):
            yield from _identifiers(v)
        elif isinstance(v, tuple):
            for item in v:
                if isinstance(item, t.Node):
                    yield from _identifiers(item)
                elif (isinstance(item, tuple) and len(item) == 2
                        and isinstance(item[0], t.Node)):
                    yield from _identifiers(item[0])
                    yield from _identifiers(item[1])


def _rewrite_identifiers(expr, fn):
    """Structurally rewrite Identifier leaves via ``fn`` (not descending
    into subqueries, whose identifiers live in their own scopes)."""
    if isinstance(expr, t.Identifier):
        return fn(expr)
    if isinstance(expr, (t.InSubquery, t.Exists, t.ScalarSubquery)) \
            or not hasattr(expr, "__dataclass_fields__"):
        return expr
    changes = {}
    for f in expr.__dataclass_fields__:
        v = getattr(expr, f)
        if isinstance(v, t.Node):
            nv = _rewrite_identifiers(v, fn)
            if nv is not v:
                changes[f] = nv
        elif isinstance(v, tuple):
            items = []
            changed = False
            for item in v:
                if isinstance(item, t.Node):
                    ni = _rewrite_identifiers(item, fn)
                    changed |= ni is not item
                    items.append(ni)
                elif isinstance(item, tuple):
                    ni = tuple(_rewrite_identifiers(s, fn)
                               if isinstance(s, t.Node) else s
                               for s in item)
                    changed |= ni != item
                    items.append(ni)
                else:
                    items.append(item)
            if changed:
                changes[f] = tuple(items)
    return dataclasses.replace(expr, **changes) if changes else expr


def _substitute_select_aliases(expr: t.Expression, q: t.Query):
    """Replace single-part identifiers naming a select-list alias with
    that item's expression (one shot, no re-substitution) — the
    StatementAnalyzer ORDER-BY-scope rule that makes aliases usable
    inside ORDER BY expressions."""
    aliases = {item.alias: item.expr for item in q.select
               if item.alias is not None
               and not isinstance(item.expr, t.Star)}

    def fn(ident: t.Identifier):
        if len(ident.parts) == 1 and ident.parts[0] in aliases:
            return aliases[ident.parts[0]]
        return ident

    return _rewrite_identifiers(expr, fn)


def _contains_subquery(expr: t.Node) -> bool:
    if isinstance(expr, (t.InSubquery, t.Exists, t.ScalarSubquery)):
        return True
    for f in getattr(expr, "__dataclass_fields__", {}):
        v = getattr(expr, f)
        if isinstance(v, t.Node) and _contains_subquery(v):
            return True
        if isinstance(v, tuple):
            for item in v:
                if isinstance(item, t.Node) and _contains_subquery(item):
                    return True
                if isinstance(item, tuple):
                    for sub in item:
                        if isinstance(sub, t.Node) and _contains_subquery(sub):
                            return True
    return False


def _contains_aggregate(expr: t.Node) -> bool:
    if (isinstance(expr, t.FunctionCall) and expr.name in AGG_NAMES
            and expr.window is None):
        return True
    if isinstance(expr, (t.InSubquery, t.Exists, t.ScalarSubquery)):
        return False
    for f in getattr(expr, "__dataclass_fields__", {}):
        v = getattr(expr, f)
        if isinstance(v, t.Node) and _contains_aggregate(v):
            return True
        if isinstance(v, tuple):
            for item in v:
                if isinstance(item, t.Node) and _contains_aggregate(item):
                    return True
                if isinstance(item, tuple):
                    for sub in item:
                        if isinstance(sub, t.Node) and _contains_aggregate(sub):
                            return True
    return False


def split_conjuncts(expr: Optional[t.Expression]) -> List[t.Expression]:
    if expr is None:
        return []
    if isinstance(expr, t.LogicalBinary) and expr.op == "and":
        return split_conjuncts(expr.left) + split_conjuncts(expr.right)
    return [expr]


def _split_disjuncts(expr: t.Expression) -> List[t.Expression]:
    if isinstance(expr, t.LogicalBinary) and expr.op == "or":
        return _split_disjuncts(expr.left) + _split_disjuncts(expr.right)
    return [expr]


def _and_asts(parts: List[t.Expression]) -> t.Expression:
    out = parts[0]
    for p in parts[1:]:
        out = t.LogicalBinary("and", out, p)
    return out


def _or_asts(parts: List[t.Expression]) -> t.Expression:
    out = parts[0]
    for p in parts[1:]:
        out = t.LogicalBinary("or", out, p)
    return out


def factor_common_disjunct_conjuncts(expr: t.Expression) -> t.Expression:
    """(A AND X) OR (A AND Y) -> A AND (X OR Y): conjuncts shared (by AST
    equality) between every disjunct hoist to the top AND level — the
    ExtractCommonPredicatesExpressionRewriter role
    (presto-main/.../sql/planner/iterative/rule/
    ExtractCommonPredicatesExpressionRewriter.java).  TPC-DS q41's
    correlation is only extractable after this factoring."""
    disjuncts = _split_disjuncts(expr)
    if len(disjuncts) < 2:
        return expr
    per = [split_conjuncts(d) for d in disjuncts]
    # dedupe: (A AND A AND X) repeats A in per[0]; keeping both would
    # double-remove below (historically a ValueError on rest.remove)
    common: List[t.Expression] = []
    for c in per[0]:
        if any(c == seen for seen in common):
            continue
        if all(any(c == o for o in others) for others in per[1:]):
            common.append(c)
    if not common:
        return expr
    rests = []
    for conj in per:
        # drop EVERY occurrence of each common conjunct (A AND A == A)
        rest = [c for c in conj
                if not any(c == h for h in common)]
        if not rest:        # a disjunct reduced to TRUE: OR collapses
            return _and_asts(common)
        rests.append(_and_asts(rest))
    return _and_asts(common + [_or_asts(rests)])


# ---------------------------------------------------------------------------
# Metadata
# ---------------------------------------------------------------------------

class Metadata:
    """Catalog facade (Metadata.java:66 role)."""

    def __init__(self, registry: ConnectorRegistry, default_catalog: str):
        self.registry = registry
        self.default_catalog = default_catalog

    def split_name(self, parts: Tuple[str, ...]) -> Tuple[str, str]:
        if len(parts) == 1:
            return self.default_catalog, parts[0]
        if len(parts) == 2:
            return parts[0], parts[1]
        return parts[0], parts[-1]  # catalog.schema.table

    def resolve_table(self, parts: Tuple[str, ...]):
        catalog, table = self.split_name(parts)
        conn = self.registry.get(catalog)
        handle = conn.get_table(table)
        if handle is None:
            raise SqlAnalysisError(f"table {'.'.join(parts)} does not exist")
        schema = conn.table_schema(handle)
        return catalog, table, conn, schema

    # -- views (ConnectorMetadata.getView / StatementAnalyzer view
    #    expansion role) ----------------------------------------------------
    def get_view(self, parts: Tuple[str, ...]) -> Optional[str]:
        return self.registry.views.get(self.split_name(parts))

    def create_view(self, parts: Tuple[str, ...], sql: str,
                    replace: bool) -> None:
        key = self.split_name(parts)
        if not replace and key in self.registry.views:
            raise SqlAnalysisError(f"view {'.'.join(key)} already exists")
        self.registry.views[key] = sql

    def drop_view(self, parts: Tuple[str, ...], if_exists: bool) -> None:
        key = self.split_name(parts)
        if key not in self.registry.views:
            if if_exists:
                return
            raise SqlAnalysisError(f"view {'.'.join(key)} does not exist")
        del self.registry.views[key]


# ---------------------------------------------------------------------------
# Expression translation
# ---------------------------------------------------------------------------

class Translator:
    """AST expression -> RowExpression over a scope's channels."""

    def __init__(self, scope: Scope,
                 grouped: Optional["GroupingContext"] = None,
                 windows: Optional[Dict[t.Expression, RowExpression]] = None,
                 subquery_refs: Optional[Dict[int, RowExpression]] = None):
        self.scope = scope
        self.grouped = grouped
        self.windows = windows
        # id(AST subquery node) -> hoisted RowExpression: subqueries the
        # planner has already attached as channels (apply/decorrelation)
        self.subquery_refs = subquery_refs
        self.lambda_env: Dict[str, T.Type] = {}  # lambda params in scope

    def translate(self, expr: t.Expression) -> RowExpression:
        if self.subquery_refs is not None:
            hit = self.subquery_refs.get(id(expr))
            if hit is not None:
                return hit
        if self.windows is not None:
            hit = self.windows.get(expr)
            if hit is not None:
                return hit
        if isinstance(expr, t.FunctionCall) and expr.window is not None:
            raise SqlAnalysisError(
                f"window function {expr.name} in an unsupported position")
        if self.grouped is not None:
            hit = self.grouped.lookup(expr)
            if hit is not None:
                return hit
            if isinstance(expr, t.FunctionCall) \
                    and expr.name == "grouping":
                return self._translate_grouping(expr)
            if isinstance(expr, t.FunctionCall) and expr.name in AGG_NAMES:
                raise SqlAnalysisError(
                    f"aggregate {expr.name} not found in grouping context")
        return self._translate(expr)

    def _translate_grouping(self, expr: t.FunctionCall) -> RowExpression:
        """grouping(c1, ..) -> bitmask (1 = aggregated away), from the
        grouping-sets $grouping_id channel (GroupIdOperator's groupId)."""
        positions = []
        for arg in expr.args:
            pos = None
            for i, g in enumerate(self.grouped.group_asts):
                if g == arg:
                    pos = i
                    break
            if pos is None:
                raise SqlAnalysisError(
                    "grouping() argument must be a grouping column")
            positions.append(pos)
        gch = self.grouped.grouping_id_channel
        if gch is None:
            return B.const(0, T.BIGINT)   # plain GROUP BY: all grouped
        gid = B.ref(gch, T.BIGINT)
        n = len(positions)
        out: RowExpression = B.const(0, T.BIGINT)
        for j, pos in enumerate(positions):
            bit = B.call("mod",
                         B.call("divide", gid,
                                B.const(1 << pos, T.BIGINT)),
                         B.const(2, T.BIGINT))
            term = (bit if n - 1 - j == 0 else
                    B.call("multiply", bit,
                           B.const(1 << (n - 1 - j), T.BIGINT)))
            out = B.call("add", out, term)
        return out

    def _translate(self, e: t.Expression) -> RowExpression:
        if isinstance(e, t.Identifier):
            if len(e.parts) == 1 and e.parts[0] in self.lambda_env:
                from presto_tpu.expr.ir import VarRef

                return VarRef(e.parts[0], self.lambda_env[e.parts[0]])
            if len(e.parts) == 1 and e.parts[0] in (
                    "current_date", "current_timestamp", "localtimestamp"):
                # fixed at query (translation) start, Presto semantics
                import time as _time

                now_us = int(_time.time() * 1e6)
                if e.parts[0] == "current_date":
                    return B.const(now_us // 86_400_000_000, T.DATE)
                return B.const(now_us, T.TIMESTAMP)
            idx = self.scope.try_resolve(e.parts)
            if idx is None:
                # row-field access spelled as a qualified name: resolve the
                # longest prefix as a column, the rest as ROW fields
                rf = self._try_row_fields(e.parts)
                if rf is not None:
                    return rf
                if self.grouped is not None:
                    raise SqlAnalysisError(
                        f"column {e} must appear in GROUP BY or inside an "
                        "aggregate")
                raise SqlAnalysisError(f"column {e} cannot be resolved")
            return B.ref(idx, self.scope.fields[idx].type)
        if isinstance(e, t.NumberLiteral):
            return _number_literal(e.text)
        if isinstance(e, t.StringLiteral):
            return B.const(e.value, T.VARCHAR)
        if isinstance(e, t.BooleanLiteral):
            return B.const(e.value, T.BOOLEAN)
        if isinstance(e, t.NullLiteral):
            return B.null(T.UNKNOWN)
        if isinstance(e, t.TypedLiteral):
            if e.type_name == "decimal":
                # DECIMAL '1.2': precision/scale inferred from the text
                # (DecimalParseResult role)
                txt = e.value.strip().lstrip("+-")
                digits = txt.replace(".", "")
                scale = len(txt.split(".")[1]) if "." in txt else 0
                typ: T.Type = T.DecimalType(
                    "decimal", precision=max(len(digits), 1), scale=scale)
            else:
                typ = T.parse_type(e.type_name)
            return B.const(e.value, typ)
        if isinstance(e, t.IntervalLiteral):
            raise SqlAnalysisError(
                "interval literal outside +/- date arithmetic")
        if isinstance(e, t.ArithmeticBinary):
            return self._arithmetic(e)
        if isinstance(e, t.ArithmeticUnary):
            arg = self.translate(e.expr)
            if isinstance(arg, Constant) and arg.value is not None:
                return B.const(-arg.value, arg.type)
            return B.call("negate", arg)
        if isinstance(e, t.Comparison):
            return B.comparison(e.op, self.translate(e.left),
                                self.translate(e.right))
        if isinstance(e, t.Between):
            v = self.translate(e.expr)
            out = B.between(v, self.translate(e.low), self.translate(e.high))
            return B.not_(out) if e.negated else out
        if isinstance(e, t.InList):
            v = self.translate(e.expr)
            out = B.in_(v, [self.translate(i) for i in e.items])
            return B.not_(out) if e.negated else out
        if isinstance(e, t.Like):
            v = self.translate(e.expr)
            pat = self.translate(e.pattern)
            args = (v, pat)
            if e.escape is not None:
                args = args + (self.translate(e.escape),)
            out = B.call("like", *args)
            return B.not_(out) if e.negated else out
        if isinstance(e, t.IsNull):
            v = self.translate(e.expr)
            name = "is_not_null" if e.negated else "is_null"
            return B.call(name, v)
        if isinstance(e, t.Not):
            return B.not_(self.translate(e.expr))
        if isinstance(e, t.LogicalBinary):
            fn = B.and_ if e.op == "and" else B.or_
            return fn(self.translate(e.left), self.translate(e.right))
        if isinstance(e, t.Case):
            whens = []
            for cond, val in e.whens:
                if e.operand is not None:
                    c = B.comparison("=", self.translate(e.operand),
                                     self.translate(cond))
                else:
                    c = self.translate(cond)
                whens.append((c, self.translate(val)))
            default = (self.translate(e.default)
                       if e.default is not None else None)
            # unify result types (numeric widening)
            rtype = _common_type(
                [v.type for _, v in whens]
                + ([default.type] if default is not None else []))
            whens = [(c, _coerce(v, rtype)) for c, v in whens]
            if default is not None:
                default = _coerce(default, rtype)
            return B.case_when(whens, default, rtype)
        if isinstance(e, t.Coalesce):
            args = [self.translate(a) for a in e.args]
            rtype = _common_type([a.type for a in args])
            return B.coalesce(*[_coerce(a, rtype) for a in args])
        if isinstance(e, t.NullIf):
            first = self.translate(e.first)
            second = self.translate(e.second)
            cond = B.comparison("=", first, second)
            return B.case_when([(cond, B.null(first.type))], first,
                               first.type)
        if isinstance(e, t.Cast):
            return B.cast(self.translate(e.expr), T.parse_type(e.type_name))
        if isinstance(e, t.TryCast):
            arg = self.translate(e.expr)
            to = T.parse_type(e.type_name)
            if arg.type == to or isinstance(arg.type, T.UnknownType):
                return B.cast(arg, to)
            fn = F.resolve_try_cast(arg.type, to)
            return Call("try_cast", (arg,), to, fn)
        if isinstance(e, t.Extract):
            return B.call(f"extract_{e.field.lower()}",
                          self.translate(e.expr))
        if isinstance(e, t.FunctionCall):
            if e.name in AGG_NAMES:
                raise SqlAnalysisError(
                    f"aggregate {e.name} used outside aggregation context")
            return self._function_call(e)
        if isinstance(e, t.ArrayConstructor):
            items = [self.translate(i) for i in e.items]
            et = _common_type([i.type for i in items]) if items else T.UNKNOWN
            items = [_coerce(i, et) for i in items]
            rt = T.ArrayType("array", element=et)
            fn = F.resolve_array_constructor(rt, len(items))
            return Call("$array", tuple(items), rt, fn)
        if isinstance(e, t.Subscript):
            base = self.translate(e.base)
            if isinstance(base.type, T.RowType):
                idx = self.translate(e.index)
                if not isinstance(idx, Constant):
                    raise SqlAnalysisError("row subscript must be constant")
                i = int(idx.value) - 1
                fn = F.resolve_row_field_index(base.type, i)
                return Call("row_field", (base,), fn.result_type, fn)
            idx = self.translate(e.index)
            if isinstance(base.type, T.MapType):
                idx = _coerce(idx, base.type.key)
            fn = F.resolve_scalar("subscript", [base.type, idx.type])
            return Call("subscript", (base, idx), fn.result_type, fn)
        if isinstance(e, t.Deref):
            base = self.translate(e.base)
            if not isinstance(base.type, T.RowType):
                raise SqlAnalysisError(
                    f"cannot dereference {base.type.display()}")
            fn, _ = F.resolve_row_field(base.type, e.field)
            return Call("row_field", (base,), fn.result_type, fn)
        if isinstance(e, t.Lambda):
            raise SqlAnalysisError(
                "lambda expression outside a higher-order function")
        raise SqlAnalysisError(
            f"unsupported expression {type(e).__name__}")

    def _try_row_fields(self, parts) -> Optional[RowExpression]:
        for k in range(len(parts) - 1, 0, -1):
            idx = self.scope.try_resolve(parts[:k])
            if idx is None:
                continue
            expr: RowExpression = B.ref(idx, self.scope.fields[idx].type)
            ok = True
            for field in parts[k:]:
                if not isinstance(expr.type, T.RowType):
                    ok = False
                    break
                try:
                    fn, _ = F.resolve_row_field(expr.type, field)
                except F.FunctionError:
                    ok = False
                    break
                expr = Call("row_field", (expr,), fn.result_type, fn)
            if ok:
                return expr
        return None

    def _translate_lambda(self, lam: t.Lambda,
                          param_types: List[T.Type]):
        from presto_tpu.expr.ir import LambdaExpr

        if len(lam.params) != len(param_types):
            raise SqlAnalysisError(
                f"lambda takes {len(lam.params)} parameters, expected "
                f"{len(param_types)}")
        saved = dict(self.lambda_env)
        self.lambda_env.update(zip(lam.params, param_types))
        try:
            body = self.translate(lam.body)
        finally:
            self.lambda_env = saved
        return LambdaExpr(tuple(lam.params), tuple(param_types), body,
                          body.type)

    _CONST_FNS = {"pi": 3.141592653589793, "e": 2.718281828459045,
                  "nan": float("nan"), "infinity": float("inf")}

    def _function_call(self, e: t.FunctionCall) -> RowExpression:
        name = e.name.lower()
        if any(isinstance(a, t.Lambda) for a in e.args):
            return self._higher_order_call(name, e)
        if name in self._CONST_FNS and not e.args:
            return B.const(self._CONST_FNS[name], T.DOUBLE)
        if name in ("now", "current_timestamp") and not e.args:
            import time as _time

            return B.const(int(_time.time() * 1e6), T.TIMESTAMP)
        if name == "current_date" and not e.args:
            import time as _time

            return B.const(int(_time.time()) // 86_400, T.DATE)
        if name == "typeof" and len(e.args) == 1:
            return B.const(self.translate(e.args[0]).type.display(),
                           T.VARCHAR)
        if name == "if" and len(e.args) in (2, 3):
            cond = self.translate(e.args[0])
            then = self.translate(e.args[1])
            els = self.translate(e.args[2]) if len(e.args) == 3 else None
            rtype = _common_type([then.type]
                                 + ([els.type] if els is not None else []))
            then = _coerce(then, rtype)
            if els is not None:
                els = _coerce(els, rtype)
            return B.case_when([(cond, then)], els, rtype)
        if name == "round" and len(e.args) == 2:
            digits = self.translate(e.args[1])
            if not isinstance(digits, Constant):
                raise SqlAnalysisError("round(x, d) requires constant d")
            return B.round_digits(self.translate(e.args[0]),
                                  int(digits.value))
        if name in ("date_format", "format_datetime") and len(e.args) == 2:
            x = self.translate(e.args[0])
            fmt = self.translate(e.args[1])
            if not (isinstance(fmt, Constant)
                    and isinstance(fmt.value, str)):
                raise SqlAnalysisError(
                    f"{name} format must be a constant string")
            resolver = (F.resolve_date_format if name == "date_format"
                        else F.resolve_format_datetime)
            fn = resolver(x.type, fmt.value)
            return Call(name, (x, fmt), fn.result_type, fn)
        if name in ("date_trunc", "date_add", "date_diff") and e.args:
            unit_rex = self.translate(e.args[0])
            if not (isinstance(unit_rex, Constant)
                    and isinstance(unit_rex.value, str)):
                raise SqlAnalysisError(f"{name} unit must be a constant "
                                       "string")
            unit = unit_rex.value.lower()
            if name == "date_trunc":
                return B.call(f"date_trunc_{unit}",
                              self.translate(e.args[1]))
            if name == "date_diff":
                return B.call(f"date_diff_{unit}",
                              self.translate(e.args[1]),
                              self.translate(e.args[2]))
            # date_add(unit, value, x)
            n = self.translate(e.args[1])
            x = self.translate(e.args[2])
            if unit == "day":
                return B.call("add_days", x, n)
            if unit == "week":
                return B.call("add_days", x,
                              B.call("multiply", n, B.const(7, T.INTEGER)))
            if unit == "month":
                return B.call("add_months", x, n)
            if unit == "quarter":
                return B.call("add_months", x,
                              B.call("multiply", n, B.const(3, T.INTEGER)))
            if unit == "year":
                return B.call("add_months", x,
                              B.call("multiply", n, B.const(12, T.INTEGER)))
            if x.type.name == "timestamp":
                scale = {"hour": 3_600_000_000, "minute": 60_000_000,
                         "second": 1_000_000, "millisecond": 1_000}[unit]
                return B.call("add", x, B.call(
                    "multiply", B.cast(n, T.BIGINT),
                    B.const(scale, T.BIGINT)))
            raise SqlAnalysisError(f"date_add unit {unit!r} on "
                                   f"{x.type.display()}")
        return B.call(name, *[self.translate(a) for a in e.args])

    def _higher_order_call(self, name: str,
                           e: t.FunctionCall) -> RowExpression:
        """Lambda-taking array/map functions (the reference's
        LambdaDefinitionExpression call sites)."""
        from presto_tpu.expr.ir import LambdaExpr

        first = self.translate(e.args[0])
        ft = first.type
        if name in ("transform", "filter", "any_match", "all_match",
                    "none_match"):
            if not isinstance(ft, T.ArrayType):
                raise SqlAnalysisError(f"{name} expects an array")
            lam = self._translate_lambda(e.args[1], [ft.element])
            fn = resolve_scalar(name, [ft, lam.type])
            return Call(name, (first, lam), fn.result_type, fn)
        if name in ("map_filter", "transform_values", "transform_keys"):
            if not isinstance(ft, T.MapType):
                raise SqlAnalysisError(f"{name} expects a map")
            lam = self._translate_lambda(e.args[1], [ft.key, ft.value])
            fn = resolve_scalar(name, [ft, lam.type])
            return Call(name, (first, lam), fn.result_type, fn)
        if name == "zip_with":
            if not isinstance(ft, T.ArrayType) or len(e.args) != 3:
                raise SqlAnalysisError("zip_with(a, b, (x, y) -> ...)")
            second = self.translate(e.args[1])
            if not isinstance(second.type, T.ArrayType):
                raise SqlAnalysisError("zip_with expects two arrays")
            lam = self._translate_lambda(
                e.args[2], [ft.element, second.type.element])
            fn = resolve_scalar("zip_with", [ft, second.type, lam.type])
            return Call("zip_with", (first, second, lam),
                        fn.result_type, fn)
        if name == "reduce":
            if not isinstance(ft, T.ArrayType) or len(e.args) != 4:
                raise SqlAnalysisError(
                    "reduce(array, init, (s, x) -> ..., s -> ...)")
            init = self.translate(e.args[1])
            state_t = init.type
            comb = self._translate_lambda(e.args[2], [state_t, ft.element])
            if comb.type != state_t:
                body = _coerce(comb.body, state_t)
                comb = LambdaExpr(comb.params, comb.param_types, body,
                                  state_t)
            fin = self._translate_lambda(e.args[3], [state_t])
            fn = resolve_scalar(
                "reduce", [ft, state_t, comb.type, fin.type])
            return Call("reduce", (first, init, comb, fin),
                        fn.result_type, fn)
        raise SqlAnalysisError(f"{name} does not take a lambda")

    def _arithmetic(self, e: t.ArithmeticBinary) -> RowExpression:
        # date +/- interval folds into add_days/add_months with constant
        op_name = {"+": "add", "-": "subtract", "*": "multiply",
                   "/": "divide", "%": "modulus"}[e.op]
        if isinstance(e.right, t.IntervalLiteral) and e.op in "+-":
            base = self.translate(e.left)
            return _date_interval(base, e.right, negate=(e.op == "-"))
        if isinstance(e.left, t.IntervalLiteral) and e.op == "+":
            base = self.translate(e.right)
            return _date_interval(base, e.left, negate=False)
        return B.call(op_name, self.translate(e.left),
                      self.translate(e.right))


def _date_interval(base: RowExpression, iv: t.IntervalLiteral,
                   negate: bool) -> RowExpression:
    n = int(iv.value) * iv.sign * (-1 if negate else 1)
    if iv.unit == "year":
        return B.call("add_months", base, B.const(12 * n, T.INTEGER))
    if iv.unit == "month":
        return B.call("add_months", base, B.const(n, T.INTEGER))
    if iv.unit == "day":
        return B.call("add_days", base, B.const(n, T.INTEGER))
    if base.type.name == "timestamp":
        scale = {"hour": 3_600_000_000, "minute": 60_000_000,
                 "second": 1_000_000}[iv.unit]
        return B.call("add", base, B.const(n * scale, T.BIGINT))
    raise SqlAnalysisError(f"interval unit {iv.unit} on {base.type.name}")


def _number_literal(text: str) -> Constant:
    if "." in text or "e" in text.lower():
        return B.const(float(text), T.DOUBLE)
    v = int(text)
    if -(2 ** 31) <= v < 2 ** 31:
        return B.const(v, T.INTEGER)
    return B.const(v, T.BIGINT)


_NUM_ORDER = ["tinyint", "smallint", "integer", "bigint", "real", "double"]


def _common_type(types: List[T.Type]) -> T.Type:
    known = [x for x in types if not isinstance(x, T.UnknownType)]
    if not known:
        return T.UNKNOWN
    out = known[0]
    for x in known[1:]:
        if x == out:
            continue
        if x.name in _NUM_ORDER and out.name in _NUM_ORDER:
            out = x if (_NUM_ORDER.index(x.name)
                        > _NUM_ORDER.index(out.name)) else out
        elif T.is_string(x) and T.is_string(out):
            out = T.VARCHAR
        elif isinstance(x, T.DecimalType) or isinstance(out, T.DecimalType):
            out = T.DOUBLE if (x.name in _NUM_ORDER
                               or out.name in _NUM_ORDER) else out
        else:
            cs = T.common_super_type(out, x)
            if cs is None:
                raise SqlAnalysisError(
                    f"mismatched types {out.display()} vs {x.display()}")
            out = cs
    return out


def _coerce(expr: RowExpression, typ: T.Type) -> RowExpression:
    if expr.type == typ or isinstance(expr.type, T.UnknownType):
        return expr
    return B.cast(expr, typ)


# ---------------------------------------------------------------------------
# Grouping context
# ---------------------------------------------------------------------------

class GroupingContext:
    """Maps group-by ASTs and aggregate-call ASTs to agg-output channels."""

    def __init__(self, group_asts: List[t.Expression],
                 agg_asts: List[t.FunctionCall],
                 out_fields: List[Field],
                 grouping_id_channel: Optional[int] = None):
        self.group_asts = group_asts
        self.agg_asts = agg_asts
        self.out_fields = out_fields
        # GROUPING SETS only: channel of the per-branch grouping-id
        # bitmask (bit i set = key i aggregated away in this row)
        self.grouping_id_channel = grouping_id_channel

    def lookup(self, expr: t.Expression) -> Optional[RowExpression]:
        for i, g in enumerate(self.group_asts):
            if expr == g:
                return B.ref(i, self.out_fields[i].type)
        base = len(self.group_asts)
        for j, a in enumerate(self.agg_asts):
            if expr == a:
                return B.ref(base + j, self.out_fields[base + j].type)
        return None


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RelationPlan:
    node: PlanNode
    scope: Scope


class Planner:
    """One instance per statement (LogicalPlanner.java:176 role)."""

    def __init__(self, metadata: Metadata):
        self.metadata = metadata
        self.ctes: List[Dict[str, t.Query]] = []

    # --- entry -------------------------------------------------------------
    def plan(self, query: t.Query) -> OutputNode:
        rel = self.plan_query(query, None)
        cols = tuple((f.name, f.type) for f in rel.scope.fields)
        return OutputNode(rel.node, cols)

    # --- query -------------------------------------------------------------
    def plan_query(self, q: t.Node, outer: Optional[Scope]) -> RelationPlan:
        if q.with_queries:
            self.ctes.append(dict(q.with_queries))
        try:
            if isinstance(q, t.SetOperation):
                return self._plan_set_operation(q, outer)
            return self._plan_query_body(q, outer)
        finally:
            if q.with_queries:
                self.ctes.pop()

    def _plan_set_operation(self, q: t.SetOperation,
                            outer: Optional[Scope]) -> RelationPlan:
        """UNION [ALL] / INTERSECT / EXCEPT.  Branch outputs are coerced to
        common types; DISTINCT semantics via aggregation over all channels;
        INTERSECT/EXCEPT via (anti-)semijoin over distinct branches —
        the same shapes the reference's SetOperationNodes lower to.
        Like the reference (Presto 328), INTERSECT ALL / EXCEPT ALL are
        not supported."""
        left = self.plan_query(q.left, outer)
        right = self.plan_query(q.right, outer)
        ltypes = [f.type for f in left.scope.fields]
        rtypes = [f.type for f in right.scope.fields]
        if len(ltypes) != len(rtypes):
            raise SqlAnalysisError(
                f"{q.op} branches have {len(ltypes)} vs {len(rtypes)} "
                "columns")
        common = [_common_type([a, b]) for a, b in zip(ltypes, rtypes)]

        def coerced(rel: RelationPlan) -> PlanNode:
            node = rel.node
            exprs = []
            for i, typ in enumerate(common):
                ref = B.ref(i, node.types[i])
                exprs.append(_coerce(ref, typ))
            if all(isinstance(e, InputRef) and e.type == common[i]
                   for i, e in enumerate(exprs)):
                return node
            cols = tuple((left.scope.fields[i].name, typ)
                         for i, typ in enumerate(common))
            return ProjectNode(node, tuple(exprs), cols)

        lnode, rnode = coerced(left), coerced(right)
        out_cols = tuple((f.name, typ)
                         for f, typ in zip(left.scope.fields, common))
        fields = [Field(f.name, None, typ)
                  for f, typ in zip(left.scope.fields, common)]
        all_ch = tuple(range(len(common)))

        if q.op == "union":
            node: PlanNode = UnionNode((lnode, rnode), out_cols)
            if not q.all:
                node = AggregationNode(node, all_ch, (), out_cols)
        elif q.op in ("intersect", "except"):
            if q.all:
                raise SqlAnalysisError(
                    f"{q.op.upper()} ALL is not supported")
            # the reference's SetOperationNodeTranslator shape: union both
            # branches with per-side marker columns, GROUP BY all output
            # channels (NULL keys group together — distinct semantics,
            # unlike join matching), then filter on the side counts
            def marked(node: PlanNode, lv: int, rv: int) -> PlanNode:
                exprs = tuple(
                    [B.ref(i, typ) for i, typ in enumerate(common)]
                    + [B.const(lv, T.BIGINT), B.const(rv, T.BIGINT)])
                cols = out_cols + (("$l", T.BIGINT), ("$r", T.BIGINT))
                return ProjectNode(node, exprs, cols)

            u_cols = out_cols + (("$l", T.BIGINT), ("$r", T.BIGINT))
            u = UnionNode((marked(lnode, 1, 0), marked(rnode, 0, 1)),
                          u_cols)
            nch = len(common)
            aggs = (PlanAggregate(resolve_aggregate("sum", T.BIGINT), nch),
                    PlanAggregate(resolve_aggregate("sum", T.BIGINT),
                                  nch + 1))
            agg_cols = out_cols + (("$lc", T.BIGINT), ("$rc", T.BIGINT))
            agg = AggregationNode(u, all_ch, aggs, agg_cols)
            lc = B.ref(nch, T.BIGINT)
            rc = B.ref(nch + 1, T.BIGINT)
            in_left = B.comparison(">", lc, B.const(0, T.BIGINT))
            in_right = B.comparison(
                ">" if q.op == "intersect" else "=",
                rc, B.const(0, T.BIGINT))
            filt = FilterNode(agg, B.and_(in_left, in_right))
            node = ProjectNode(
                filt,
                tuple(B.ref(i, typ) for i, typ in enumerate(common)),
                out_cols)
        else:
            raise SqlAnalysisError(f"unknown set operation {q.op}")

        out = RelationPlan(node, Scope(fields, outer))
        if q.order_by:
            keys = []
            for item in q.order_by:
                ch = self._set_op_order_channel(item.expr, out.scope)
                keys.append((ch, item.ascending, item.nulls_first))
            out = RelationPlan(SortNode(out.node, tuple(keys)), out.scope)
        if q.limit is not None:
            out = RelationPlan(LimitNode(out.node, q.limit), out.scope)
        return out

    def _set_op_order_channel(self, e: t.Expression, scope: Scope) -> int:
        if isinstance(e, t.NumberLiteral) and e.text.isdigit():
            n = int(e.text)
            if not (1 <= n <= len(scope.fields)):
                raise SqlAnalysisError(f"ORDER BY position {n} out of range")
            return n - 1
        if isinstance(e, t.Identifier) and len(e.parts) == 1:
            idx = scope.try_resolve(e.parts)
            if idx is not None:
                return idx
        raise SqlAnalysisError(
            "set-operation ORDER BY must reference an output column")

    def _plan_query_body(self, q: t.Query,
                         outer: Optional[Scope]) -> RelationPlan:
        # FROM
        if q.relations:
            rel = self.plan_relation(q.relations[0], outer)
            for r in q.relations[1:]:
                right = self.plan_relation(r, outer)
                rel = self._cross_join(rel, right)
        else:
            rel = RelationPlan(ValuesNode((("dummy", T.BIGINT),), ((0,),)),
                               Scope([Field("dummy", None, T.BIGINT)]))
        rel.scope.parent = outer

        # WHERE (incl. subquery conjuncts)
        rel = self._plan_where(rel, q.where)

        has_aggs = (q.group_by
                    or any(_contains_aggregate(i.expr) for i in q.select)
                    or (q.having is not None
                        and _contains_aggregate(q.having)))
        if has_aggs:
            if q.grouping_sets is not None:
                rel, grouping = self._plan_grouping_sets(rel, q)
            else:
                rel, grouping = self._plan_aggregation(rel, q)
            # HAVING: plain conjuncts filter; subquery conjuncts transform
            plain_h: List[t.Expression] = []
            for c in split_conjuncts(q.having):
                if _contains_subquery(c):
                    rel = self._apply_subquery_conjunct(rel, c, grouping)
                else:
                    plain_h.append(c)
            if plain_h:
                htr = Translator(rel.scope, grouping)
                rel = RelationPlan(
                    FilterNode(rel.node,
                               _and_all([htr.translate(c)
                                         for c in plain_h])), rel.scope)
            tr = Translator(rel.scope, grouping)
        else:
            grouping = None
            tr = Translator(rel.scope)
            if q.having is not None:
                raise SqlAnalysisError("HAVING without aggregation")

        # Window functions (planned over the post-aggregation relation,
        # LogicalPlanner window-after-aggregation ordering)
        win_calls: List[t.FunctionCall] = []
        for item in q.select:
            _collect_windows(item.expr, win_calls)
        for s in q.order_by:
            _collect_windows(s.expr, win_calls)
        if win_calls:
            rel, win_map = self._plan_windows(rel, win_calls, grouping)
            tr = Translator(rel.scope, grouping, win_map)

        # scalar subqueries inside SELECT expressions (q09-style CASE
        # over subquery counts): hoist as channels first; Star expansion
        # below must not see the hidden channels
        visible_fields = list(rel.scope.fields)
        sub_refs: Dict[int, RowExpression] = {}
        for item in q.select:
            if not isinstance(item.expr, t.Star) \
                    and _contains_subquery(item.expr):
                rel = self._hoist_subqueries(rel, item.expr, sub_refs,
                                             grouping)
        if sub_refs:
            tr = Translator(rel.scope, grouping,
                            getattr(tr, "windows", None),
                            subquery_refs=sub_refs)

        # SELECT projection
        exprs: List[RowExpression] = []
        fields: List[Field] = []
        item_asts: List[Optional[t.Expression]] = []
        for item in q.select:
            if isinstance(item.expr, t.Star):
                for i, f in enumerate(visible_fields):
                    if (item.expr.qualifier is not None
                            and f.qualifier != item.expr.qualifier[0]):
                        continue
                    if f.name.startswith("$"):
                        continue  # hidden channels ($grouping_id, ...)
                    exprs.append(B.ref(i, f.type))
                    fields.append(Field(f.name, None, f.type))
                    item_asts.append(t.Identifier((f.name,))
                                     if f.qualifier is None else
                                     t.Identifier((f.qualifier, f.name)))
                continue
            rex = tr.translate(item.expr)
            name = item.alias or _derive_name(item.expr, len(fields))
            exprs.append(rex)
            fields.append(Field(name, None, rex.type))
            item_asts.append(item.expr)
        node = ProjectNode(rel.node, tuple(exprs),
                           tuple((f.name, f.type) for f in fields))
        out = RelationPlan(node, Scope(fields, outer))

        if q.distinct:
            cols = out.node.columns
            out = RelationPlan(
                AggregationNode(out.node,
                                tuple(range(len(cols))), (), cols),
                out.scope)

        # ORDER BY over the output scope (alias / ordinal / select-expr);
        # sort keys not in the select list become hidden channels,
        # projected away after the sort (the reference's hidden-symbol
        # ordering scheme in QueryPlanner)
        if q.order_by:
            keys = []
            hidden_exprs: List[RowExpression] = []
            n_visible = len(out.node.columns)
            for item in q.order_by:
                try:
                    ch = self._order_channel(item.expr, q, item_asts,
                                             out.scope)
                except SqlAnalysisError:
                    if q.distinct:
                        raise  # DISTINCT output hides source columns
                    try:
                        rex = tr.translate(item.expr)
                    except SqlAnalysisError:
                        # the expression may use select-list ALIASES
                        # (TPC-DS q36/q70/q86: CASE WHEN lochierarchy=0
                        # ...): substitute each alias with its select
                        # expression and retry over the input scope
                        rex = tr.translate(
                            _substitute_select_aliases(item.expr, q))
                    hidden_exprs.append(rex)
                    ch = n_visible + len(hidden_exprs) - 1
                keys.append((ch, item.ascending, item.nulls_first))
            sort_src = out.node
            if hidden_exprs:
                # re-project visible + hidden channels from the
                # pre-projection relation (out.node is the visible
                # ProjectNode over rel when there is no DISTINCT)
                cols = tuple(out.node.columns) + tuple(
                    (f"$sort{i}", e.type)
                    for i, e in enumerate(hidden_exprs))
                sort_src = ProjectNode(rel.node,
                                       tuple(list(exprs) + hidden_exprs),
                                       cols)
            sorted_node = SortNode(sort_src, tuple(keys))
            if hidden_exprs:
                trim = ProjectNode(
                    sorted_node,
                    tuple(InputRef(i, typ) for i, (_, typ)
                          in enumerate(out.node.columns)),
                    tuple(out.node.columns))
                out = RelationPlan(trim, out.scope)
            else:
                out = RelationPlan(sorted_node, out.scope)
        if q.limit is not None:
            out = RelationPlan(LimitNode(out.node, q.limit), out.scope)
        return out

    def _order_channel(self, e: t.Expression, q: t.Query,
                       item_asts: List[Optional[t.Expression]],
                       out_scope: Scope) -> int:
        if isinstance(e, t.NumberLiteral) and e.text.isdigit():
            n = int(e.text)
            if not (1 <= n <= len(out_scope.fields)):
                raise SqlAnalysisError(f"ORDER BY position {n} out of range")
            return n - 1
        if isinstance(e, t.Identifier) and len(e.parts) == 1:
            idx = out_scope.try_resolve(e.parts)
            if idx is not None:
                return idx
        for i, ast in enumerate(item_asts):
            if ast == e:
                return i
        raise SqlAnalysisError(
            f"ORDER BY expression must appear in the select list: {e}")

    # --- relations ---------------------------------------------------------
    def plan_relation(self, r: t.Relation,
                      outer: Optional[Scope]) -> RelationPlan:
        if isinstance(r, t.InlineValues):
            return self._plan_inline_values(r, outer)
        if isinstance(r, t.Table):
            return self._plan_table(r, outer)
        if isinstance(r, t.SubqueryRelation):
            sub = self.plan_query(r.query, outer)
            fields = []
            for i, f in enumerate(sub.scope.fields):
                name = (r.column_aliases[i] if i < len(r.column_aliases)
                        else f.name)
                fields.append(Field(name, r.alias, f.type))
            return RelationPlan(sub.node, Scope(fields, outer))
        if isinstance(r, t.Join):
            return self._plan_join(r, outer)
        if isinstance(r, t.Unnest):
            return self._plan_unnest(r, None, outer)
        raise SqlAnalysisError(f"unsupported relation {type(r).__name__}")

    def _plan_unnest(self, u: t.Unnest, left: Optional[RelationPlan],
                     outer: Optional[Scope],
                     preserve_outer: bool = False) -> RelationPlan:
        """UNNEST as a relation (standalone or CROSS JOIN UNNEST(...))."""
        from presto_tpu.exec.unnestop import _unnest_outputs
        from presto_tpu.sql.plan import UnnestNode

        if left is None:
            dummy_cols = (("$unnest_row", T.BIGINT),)
            base = RelationPlan(ValuesNode(dummy_cols, ((0,),)),
                                Scope([Field("$unnest_row", None, T.BIGINT)],
                                      outer))
        else:
            base = left
        tr = Translator(base.scope)
        args = [tr.translate(a) for a in u.args]
        for a in args:
            if not isinstance(a.type, (T.ArrayType, T.MapType)):
                raise SqlAnalysisError(
                    f"cannot unnest {a.type.display()}")
        nbase = len(base.node.columns)
        proj_exprs = tuple(
            [B.ref(i, ty) for i, (_, ty) in enumerate(base.node.columns)]
            + args)
        proj_cols = tuple(base.node.columns) + tuple(
            (f"$unnest{j}", a.type) for j, a in enumerate(args))
        proj = ProjectNode(base.node, proj_exprs, proj_cols)

        replicate = tuple(range(nbase)) if left is not None else ()
        unnest_channels = tuple(nbase + j for j in range(len(args)))
        out_cols: List[Tuple[str, T.Type]] = \
            [base.node.columns[i] for i in replicate]
        new_fields: List[Field] = [base.scope.fields[i] for i in replicate]
        produced = []
        for a in args:
            produced.extend(_unnest_outputs(a.type))
        names = list(u.column_aliases)
        for k, ty in enumerate(produced):
            name = names[k] if k < len(names) else f"col{k}"
            out_cols.append((name, ty))
            new_fields.append(Field(name, u.alias, ty))
        if u.ordinality:
            k = len(produced)
            name = names[k] if k < len(names) else "ordinality"
            out_cols.append((name, T.BIGINT))
            new_fields.append(Field(name, u.alias, T.BIGINT))
        node = UnnestNode(proj, replicate, unnest_channels, u.ordinality,
                          tuple(out_cols), outer=preserve_outer)
        return RelationPlan(node, Scope(new_fields, outer))

    def _plan_table(self, r: t.Table,
                    outer: Optional[Scope]) -> RelationPlan:
        # CTE reference?
        if len(r.name) == 1:
            for frame in reversed(self.ctes):
                if r.name[0] in frame:
                    sub = self.plan_query(frame[r.name[0]], outer)
                    qualifier = r.alias or r.name[0]
                    fields = [Field(f.name, qualifier, f.type)
                              for f in sub.scope.fields]
                    return RelationPlan(sub.node, Scope(fields, outer))
        view_sql = self.metadata.get_view(r.name)
        if view_sql is not None:
            from presto_tpu.sql.parser import parse_statement

            vkey = self.metadata.split_name(r.name)
            expanding = getattr(self, "_expanding_views", None)
            if expanding is None:
                expanding = self._expanding_views = set()
            if vkey in expanding:
                raise SqlAnalysisError(
                    f"view {'.'.join(vkey)} is recursive")
            vstmt = parse_statement(view_sql)
            if not isinstance(vstmt, (t.Query, t.SetOperation)):
                raise SqlAnalysisError(
                    f"view {'.'.join(r.name)} is not a query")
            expanding.add(vkey)
            try:
                sub = self.plan_query(vstmt, outer)
            finally:
                expanding.discard(vkey)
            qualifier = r.alias or r.name[-1]
            fields = [Field(f.name, qualifier, f.type)
                      for f in sub.scope.fields]
            return RelationPlan(sub.node, Scope(fields, outer))
        catalog, table, conn, schema = self.metadata.resolve_table(r.name)
        names = schema.column_names()
        cols = tuple((n, schema.column_type(n)) for n in names)
        node = TableScanNode(catalog, table, tuple(names), cols)
        qualifier = r.alias or r.name[-1]
        fields = [Field(n, qualifier, typ) for n, typ in cols]
        return RelationPlan(node, Scope(fields, outer))

    def _cross_join(self, left: RelationPlan,
                    right: RelationPlan) -> RelationPlan:
        cols = left.node.columns + right.node.columns
        node = JoinNode("cross", left.node, right.node, (), (), cols)
        return RelationPlan(node,
                            Scope(left.scope.fields + right.scope.fields,
                                  left.scope.parent))

    def _plan_join(self, r: t.Join,
                   outer: Optional[Scope]) -> RelationPlan:
        left = self.plan_relation(r.left, outer)
        if isinstance(r.right, t.Unnest):
            if r.kind not in ("cross", "inner", "left"):
                raise SqlAnalysisError(f"{r.kind} join with UNNEST")
            return self._plan_unnest(r.right, left, outer,
                                     preserve_outer=(r.kind == "left"))
        right = self.plan_relation(r.right, outer)
        combined = RelationPlan(
            None,  # type: ignore[arg-type]
            Scope(left.scope.fields + right.scope.fields, outer))
        if r.kind == "cross" or r.on is None:
            return self._cross_join(left, right)

        nleft = len(left.scope.fields)
        left_keys: List[int] = []
        right_keys: List[int] = []
        residuals: List[t.Expression] = []
        left_only: List[t.Expression] = []
        right_only: List[t.Expression] = []
        lscope = Scope(left.scope.fields, None)
        rscope = Scope(right.scope.fields, None)
        for c in split_conjuncts(r.on):
            side = _conjunct_side(c, lscope, rscope)
            if side == "both" and isinstance(c, t.Comparison) and c.op == "=":
                l_idx = _try_translate_side(c.left, lscope)
                r_idx = _try_translate_side(c.right, rscope)
                if l_idx is None or r_idx is None:
                    l_idx = _try_translate_side(c.right, lscope)
                    r_idx = _try_translate_side(c.left, rscope)
                if l_idx is not None and r_idx is not None:
                    left_keys.append(l_idx)
                    right_keys.append(r_idx)
                    continue
            if side == "left":
                left_only.append(c)
            elif side == "right":
                right_only.append(c)
            else:
                residuals.append(c)

        # single-side conjuncts push into the inputs when that side is
        # NOT preserved (inner both sides; outer joins only the build
        # side).  A conjunct on the PRESERVED side of an outer join only
        # gates matching — those rows split: the passing slice joins,
        # the failing slice flows through null-extended.
        preserved_only: List[t.Expression] = []
        if left_only:
            if r.kind == "inner":
                left = self._filter_rel(left, left_only)
            elif r.kind == "left":
                preserved_only = left_only
            else:
                residuals.extend(left_only)
        if right_only:
            if r.kind in ("inner", "left"):
                # left outer: filtering the build side is ON-clause
                # semantics (non-matching right rows just don't match)
                right = self._filter_rel(right, right_only)
            elif r.kind == "right":
                preserved_only = right_only
            else:
                residuals.extend(right_only)

        cols = left.node.columns + right.node.columns
        residual_rex = None
        if residuals:
            comb_tr = Translator(Scope(left.scope.fields
                                       + right.scope.fields, None))
            residual_rex = _and_all(
                [comb_tr.translate(c) for c in residuals])
        if not left_keys:
            if r.kind != "inner":
                raise SqlAnalysisError(
                    f"{r.kind} join requires at least one equi condition")
            node: PlanNode = JoinNode("cross", left.node, right.node, (), (),
                                      cols)
            if residual_rex is not None:
                node = FilterNode(node, residual_rex)
        elif r.kind == "right":
            # RIGHT JOIN = LEFT JOIN with the sides swapped, projected
            # back to the original [left cols, right cols] layout
            nright = len(right.node.columns)
            swapped_cols = right.node.columns + left.node.columns
            res = None
            if residual_rex is not None:
                from presto_tpu.sql.optimizer import remap as _remap

                mapping = {ch: (ch + nright if ch < nleft
                                else ch - nleft)
                           for ch in range(len(cols))}
                res = _remap(residual_rex, mapping)
            preserved = right.node
            ext = None
            if preserved_only:
                preserved, ext = self._split_preserved(
                    right, preserved_only,
                    lambda fail: ProjectNode(
                        fail,
                        tuple(B.null(ty)
                              for _n, ty in left.node.columns)
                        + tuple(B.ref(i, ty)
                                for i, (_n, ty)
                                in enumerate(right.node.columns)),
                        cols))
            swapped = JoinNode("left", preserved, left.node,
                               tuple(right_keys), tuple(left_keys),
                               swapped_cols, res)
            node = ProjectNode(
                swapped,
                tuple(B.ref(nright + i, ty)
                      for i, (_n, ty) in enumerate(left.node.columns))
                + tuple(B.ref(i, ty)
                        for i, (_n, ty) in enumerate(right.node.columns)),
                cols)
            if ext is not None:
                node = UnionNode((node, ext), cols)
        elif r.kind == "full":
            # FULL JOIN = LEFT JOIN  UNION ALL  (unmatched right rows,
            # null-extended) — the right/full-outer composition over
            # matched_build_mask's role (ops/join.py)
            if residual_rex is not None:
                raise SqlAnalysisError(
                    "full join residuals are not supported")
            left_join = JoinNode("left", left.node, right.node,
                                 tuple(left_keys), tuple(right_keys),
                                 cols)
            anti_b = SemiJoinNode(right.node, left.node,
                                  tuple(right_keys), tuple(left_keys),
                                  negated=True)
            extended = ProjectNode(
                anti_b,
                tuple(B.null(ty) for _n, ty in left.node.columns)
                + tuple(B.ref(i, ty)
                        for i, (_n, ty) in enumerate(right.node.columns)),
                cols)
            node = UnionNode((left_join, extended), cols)
        else:
            preserved = left.node
            ext = None
            if preserved_only and r.kind == "left":
                preserved, ext = self._split_preserved(
                    left, preserved_only,
                    lambda fail: ProjectNode(
                        fail,
                        tuple(B.ref(i, ty)
                              for i, (_n, ty)
                              in enumerate(left.node.columns))
                        + tuple(B.null(ty)
                                for _n, ty in right.node.columns),
                        cols))
            node = JoinNode(r.kind, preserved, right.node,
                            tuple(left_keys), tuple(right_keys), cols,
                            residual_rex)
            if ext is not None:
                node = UnionNode((node, ext), cols)
        return RelationPlan(node, combined.scope)

    def _split_preserved(self, rel: RelationPlan,
                         conjuncts: List[t.Expression], null_extend):
        """Split an outer join's PRESERVED side on its own ON-clause
        conjuncts: the passing slice participates in matching, the
        failing slice (including UNKNOWN) flows through null-extended."""
        tr = Translator(Scope(rel.scope.fields, None))
        pred = B.coalesce(
            _and_all([tr.translate(c) for c in conjuncts]),
            B.const(False, T.BOOLEAN))
        passing = FilterNode(rel.node, pred)
        failing = FilterNode(rel.node, B.not_(pred))
        return passing, null_extend(failing)

    def _filter_rel(self, rel: RelationPlan,
                    conjuncts: List[t.Expression]) -> RelationPlan:
        tr = Translator(Scope(rel.scope.fields, None))
        pred = _and_all([tr.translate(c) for c in conjuncts])
        return RelationPlan(FilterNode(rel.node, pred), rel.scope)

    # --- WHERE & subqueries ------------------------------------------------
    def _plan_where(self, rel: RelationPlan,
                    where: Optional[t.Expression]) -> RelationPlan:
        # plain conjuncts filter FIRST so the optimizer sees the
        # Filter-over-cross-join pattern and can extract equi joins;
        # subquery transforms stack above (AND order is irrelevant)
        plain = [c for c in split_conjuncts(where)
                 if not _contains_subquery(c)]
        if plain:
            tr = Translator(rel.scope)
            rel = RelationPlan(
                FilterNode(rel.node, _and_all([tr.translate(c)
                                               for c in plain])),
                rel.scope)
        for c in split_conjuncts(where):
            if _contains_subquery(c):
                rel = self._apply_subquery_conjunct(rel, c)
        return rel

    def _apply_subquery_conjunct(
            self, rel: RelationPlan, c: t.Expression,
            grouping: Optional[GroupingContext] = None) -> RelationPlan:
        negated = False
        inner = c
        if isinstance(inner, t.Not):
            negated = True
            inner = inner.expr
        if isinstance(inner, t.InSubquery):
            return self._plan_in_subquery(rel, inner,
                                          negated != inner.negated)
        if isinstance(inner, t.Exists):
            return self._plan_exists(rel, inner.query,
                                     negated != inner.negated)
        if isinstance(inner, t.Comparison) and not negated:
            if isinstance(inner.right, t.ScalarSubquery):
                return self._plan_scalar_compare(rel, inner.op, inner.left,
                                                 inner.right.query, grouping)
            if isinstance(inner.left, t.ScalarSubquery):
                flipped = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                           "=": "=", "<>": "<>"}[inner.op]
                return self._plan_scalar_compare(rel, flipped, inner.right,
                                                 inner.left.query, grouping)
        # general positions: EXISTS/IN under OR, scalar subqueries nested
        # in arithmetic/CASE — hoist into channels/markers and filter on
        # the rewritten expression
        return self._plan_general_subquery_filter(rel, c, grouping)

    def _plan_in_subquery(self, rel: RelationPlan, e: t.InSubquery,
                          negated: bool) -> RelationPlan:
        sub = self.plan_query(e.query, rel.scope)
        if len(sub.scope.fields) != 1:
            raise SqlAnalysisError("IN subquery must return one column")
        tr = Translator(rel.scope)
        key = tr.translate(e.expr)
        src, key_ch = _channel_for(rel, key)
        node = SemiJoinNode(src.node, sub.node, (key_ch,), (0,), negated,
                            null_aware=True)
        return RelationPlan(node, src.scope)

    def _plan_exists(self, rel: RelationPlan, q: t.Query,
                     negated: bool) -> RelationPlan:
        sub_from, corr_eq, corr_other = self._plan_correlated_from(rel, q)
        if not corr_eq:
            if corr_other:
                # correlation exists but not as extractable equality
                # conjuncts (e.g. "(a = b AND p) OR q"): constant-key
                # semi join with the WHOLE predicate as residual — the
                # nested-loop-shaped decorrelation the reference reaches
                # via TransformCorrelatedExistsToJoin
                return self._plan_exists_residual_only(
                    rel, sub_from, corr_other, negated)
            raise SqlAnalysisError(
                "uncorrelated EXISTS is not supported (always true/false)")
        outer_keys = []
        sub_keys = []
        tr = Translator(rel.scope)
        src = rel
        for sub_ch, outer_ast in corr_eq:
            key = tr.translate(outer_ast)
            src, ch = _channel_for(src, key)
            tr = Translator(src.scope)
            outer_keys.append(ch)
            sub_keys.append(sub_ch)
        residual = None
        if corr_other:
            comb = Scope(src.scope.fields + sub_from.scope.fields, None)
            ctr = Translator(comb)
            residual = _and_all([ctr.translate(c) for c in corr_other])
        node = SemiJoinNode(src.node, sub_from.node, tuple(outer_keys),
                            tuple(sub_keys), negated, residual)
        return RelationPlan(node, src.scope)

    def _plan_exists_residual_only(
            self, rel: RelationPlan, sub_from: RelationPlan,
            corr_conjuncts: List[t.Expression],
            negated: bool) -> RelationPlan:
        """EXISTS with no extractable equi-correlation: pair every outer
        row with every subquery row via a constant join key and let the
        residual (the full correlated predicate) decide matches.

        This is inherently a nested loop — O(outer x sub) residual
        evaluations, the same complexity the reference pays when its
        correlated-join rewrites bottom out in a nested-loop join;
        prefer conjunct-shaped correlation (a = b AND ...) for the hash
        path."""
        one_t = T.BIGINT

        def with_one(node: PlanNode):
            exprs = tuple(B.ref(i, ty)
                          for i, (_n, ty) in enumerate(node.columns))
            return ProjectNode(node, exprs + (B.const(1, one_t),),
                               tuple(node.columns) + (("$one", one_t),))

        src_node = with_one(rel.node)
        sub_node = with_one(sub_from.node)
        # residual channel layout: [probe cols incl. $one][build cols];
        # the hidden $one field occupies its index slot, never resolved
        comb = Scope(list(rel.scope.fields)
                     + [Field("$one", None, one_t)]
                     + list(sub_from.scope.fields), None)
        ctr = Translator(comb)
        residual = _and_all([ctr.translate(c) for c in corr_conjuncts])
        node = SemiJoinNode(src_node, sub_node,
                            (len(rel.node.columns),),
                            (len(sub_from.node.columns),),
                            negated, residual)
        proj = ProjectNode(
            node,
            tuple(B.ref(i, ty)
                  for i, (_n, ty) in enumerate(rel.node.columns)),
            tuple(rel.node.columns))
        return RelationPlan(proj, rel.scope)

    def _plan_scalar_compare(
            self, rel: RelationPlan, op: str, lhs: t.Expression,
            q: t.Query,
            grouping: Optional[GroupingContext] = None) -> RelationPlan:
        orig_fields = list(rel.scope.fields)
        orig_cols = tuple(rel.node.columns[:len(orig_fields)])
        rel2, val = self._attach_scalar_subquery(rel, q, grouping,
                                                 join_kind="inner")
        tr = Translator(rel2.scope, grouping)
        pred = B.comparison(op, tr.translate(lhs), val)
        filtered = FilterNode(rel2.node, pred)
        proj = ProjectNode(
            filtered,
            tuple(B.ref(i, ty) for i, (_n, ty) in enumerate(orig_cols)),
            orig_cols)
        return RelationPlan(proj, Scope(orig_fields, rel.scope.parent))

    def _try_uncorrelated(self, q: t.Query,
                          rel: RelationPlan) -> Optional[RelationPlan]:
        """Plan q with NO outer scope; None if it references the outer."""
        try:
            return self.plan_query(q, None)
        except SqlAnalysisError:
            return None

    def _plan_correlated_from(self, rel: RelationPlan, q: t.Query):
        """Plan a correlated subquery's FROM + local WHERE; classify
        correlated conjuncts.

        Returns (sub_plan, corr_eq, corr_other) where corr_eq is a list of
        (sub_channel, outer_ast) equality pairs and corr_other the AST
        conjuncts mixing both sides (to become join/semi residuals,
        translated over [outer fields + sub fields])."""
        if q.group_by or q.order_by or q.limit or q.distinct:
            raise SqlAnalysisError(
                "unsupported correlated subquery shape")
        sub = (self.plan_relation(q.relations[0], rel.scope)
               if q.relations else None)
        for r in (q.relations[1:] if q.relations else ()):
            sub = self._cross_join(sub, self.plan_relation(r, rel.scope))
        if sub is None:
            raise SqlAnalysisError("correlated subquery requires FROM")
        sub.scope.parent = rel.scope

        local: List[t.Expression] = []
        corr_eq: List[Tuple[int, t.Expression]] = []
        corr_other: List[t.Expression] = []
        sub_scope_only = Scope(sub.scope.fields, None)
        # factor (A AND X) OR (A AND Y) -> A AND (X OR Y) so shared
        # correlation equalities become extractable conjuncts (q41)
        conjuncts = [c2 for c in split_conjuncts(q.where)
                     for c2 in split_conjuncts(
                         factor_common_disjunct_conjuncts(c))]
        for c in conjuncts:
            if _contains_subquery(c):
                # nested subquery inside a correlated subquery: plan it
                # against the sub scope
                sub = self._apply_subquery_conjunct(sub, c)
                sub_scope_only = Scope(sub.scope.fields, None)
                continue
            locality = Scope(sub.scope.fields,
                             rel.scope).resolves_locally(c)
            if locality is True:
                local.append(c)
                continue
            if (isinstance(c, t.Comparison) and c.op == "="):
                sub_ch = _try_translate_side(c.left, sub_scope_only)
                outer_ast = c.right
                if sub_ch is None:
                    sub_ch = _try_translate_side(c.right, sub_scope_only)
                    outer_ast = c.left
                outer_ok = (Scope([], rel.scope).resolves_locally(outer_ast)
                            is False) if sub_ch is not None else False
                if sub_ch is not None and outer_ok:
                    corr_eq.append((sub_ch, outer_ast))
                    continue
            corr_other.append(c)
        if local:
            tr = Translator(sub_scope_only)
            sub = RelationPlan(
                FilterNode(sub.node,
                           _and_all([tr.translate(c) for c in local])),
                sub.scope)
        return sub, corr_eq, corr_other

    # --- general subquery hoisting (apply/decorrelation) -------------------
    # Subqueries in arbitrary expression positions — scalar subqueries
    # nested in arithmetic or CASE, EXISTS/IN under OR — hoist into
    # channels/markers joined to the relation, then the surrounding
    # expression translates normally (the reference's ApplyNode +
    # TransformCorrelated* / semiJoinOutput-symbol design).

    def _hoist_subqueries(self, rel: RelationPlan, expr: t.Node,
                          refs: Dict[int, RowExpression],
                          grouping=None) -> RelationPlan:
        """Attach every top-level subquery inside ``expr`` as a channel;
        ``refs`` maps id(ast node) -> replacement RowExpression."""
        if isinstance(expr, t.ScalarSubquery):
            rel, rex = self._attach_scalar_subquery(rel, expr.query,
                                                    grouping)
            refs[id(expr)] = rex
            return rel
        if isinstance(expr, t.Exists):
            rel, rex = self._attach_exists_marker(rel, expr.query)
            refs[id(expr)] = B.not_(rex) if expr.negated else rex
            return rel
        if isinstance(expr, t.InSubquery):
            rel, rex = self._attach_in_marker(rel, expr)
            refs[id(expr)] = B.not_(rex) if expr.negated else rex
            return rel
        for f in getattr(expr, "__dataclass_fields__", {}):
            v = getattr(expr, f)
            if isinstance(v, t.Node):
                rel = self._hoist_subqueries(rel, v, refs, grouping)
            elif isinstance(v, tuple):
                for item in v:
                    if isinstance(item, t.Node):
                        rel = self._hoist_subqueries(rel, item, refs,
                                                     grouping)
                    elif isinstance(item, tuple):
                        for sub in item:
                            if isinstance(sub, t.Node):
                                rel = self._hoist_subqueries(
                                    rel, sub, refs, grouping)
        return rel

    def _plan_general_subquery_filter(
            self, rel: RelationPlan, c: t.Expression,
            grouping=None) -> RelationPlan:
        """WHERE/HAVING conjunct with subqueries in general positions."""
        orig_fields = list(rel.scope.fields)
        orig_cols = rel.node.columns[:len(orig_fields)]
        refs: Dict[int, RowExpression] = {}
        rel = self._hoist_subqueries(rel, c, refs, grouping)
        tr = Translator(rel.scope, grouping, subquery_refs=refs)
        filtered = FilterNode(rel.node, tr.translate(c))
        proj = ProjectNode(
            filtered,
            tuple(B.ref(i, ty) for i, (_n, ty) in enumerate(orig_cols)),
            tuple(orig_cols))
        return RelationPlan(proj, Scope(orig_fields, rel.scope.parent))

    def _attach_scalar_subquery(self, rel: RelationPlan, q: t.Query,
                                grouping=None, join_kind: str = "left"
                                ) -> Tuple[RelationPlan, RowExpression]:
        """Attach a scalar subquery's single value as a channel: cross
        join + EnforceSingleRow when uncorrelated; group-by-correlation-
        keys + join for correlated aggregates.  ``join_kind`` is "left"
        in expression positions (empty groups yield NULL, SQL scalar-
        subquery semantics); the comparison-FILTER path passes "inner" —
        NULL comparisons are filtered anyway, and inner joins keep the
        optimizer's reorder/flatten paths."""
        probe = self._try_uncorrelated(q, rel)
        if probe is not None:
            nleft = len(rel.scope.fields)
            single = EnforceSingleRowNode(probe.node)
            cols = rel.node.columns + probe.node.columns
            joined = JoinNode("cross", rel.node, single, (), (), cols)
            # "$"-prefixed hidden names: no SQL identifier can spell them,
            # so an attached value named like an outer column (q58's
            # d_week_seq) can never make name resolution ambiguous
            scope = Scope(rel.scope.fields
                          + [Field(f"${f.name}", "$subquery", f.type)
                             for f in probe.scope.fields],
                          rel.scope.parent)
            return (RelationPlan(joined, scope),
                    B.ref(nleft, probe.scope.fields[0].type))
        sub_from, corr_eq, corr_other = self._plan_correlated_from(rel, q)
        if corr_other:
            raise SqlAnalysisError(
                "only equality correlation is supported in scalar "
                "subqueries")
        if not (len(q.select) == 1
                and _contains_aggregate(q.select[0].expr)):
            raise SqlAnalysisError(
                "correlated scalar subquery must be a single aggregate")
        val_proj, value_type, n_keys = self._correlated_agg_value(
            sub_from, corr_eq, q)
        src = rel
        tr = Translator(src.scope)
        outer_keys = []
        for _, outer_ast in corr_eq:
            key = tr.translate(outer_ast)
            src, ch = _channel_for(src, key)
            tr = Translator(src.scope)
            outer_keys.append(ch)
        nleft = len(src.scope.fields)
        cols = src.node.columns + val_proj.columns
        joined = JoinNode(join_kind, src.node, val_proj,
                          tuple(outer_keys), tuple(range(n_keys)), cols)
        jscope = Scope(src.scope.fields
                       + [Field(f"${n}", "$subquery", ty)
                          for n, ty in val_proj.columns],
                       src.scope.parent)
        val: RowExpression = B.ref(nleft + n_keys, value_type)
        # count over an empty group is 0, not NULL: an unmatched outer
        # row must read the count's default (the reference plants the
        # same coalesce after the decorrelating join)
        sel = q.select[0].expr
        if (isinstance(sel, t.FunctionCall)
                and sel.name.lower() in ("count", "count_if")):
            val = B.coalesce(val, B.const(0, value_type))
        return RelationPlan(joined, jscope), val

    def _correlated_agg_value(self, sub_from: RelationPlan, corr_eq,
                              q: t.Query):
        """[keys..., $value] projection of a correlated aggregate
        subquery grouped by its correlation keys."""
        sub_keys = [ch for ch, _ in corr_eq]
        agg_asts: List[t.FunctionCall] = []
        _collect_aggs(q.select[0].expr, agg_asts)
        sub_tr = Translator(sub_from.scope)
        pre_exprs = [B.ref(ch, sub_from.scope.fields[ch].type)
                     for ch in sub_keys]
        aggs: List[PlanAggregate] = []
        agg_inputs: List[RowExpression] = []
        for a in agg_asts:
            if a.is_star or not a.args:
                spec = resolve_aggregate("count", None)
                aggs.append(PlanAggregate(spec, None, a.distinct))
                continue
            arg = sub_tr.translate(a.args[0])
            agg_inputs.append(arg)
            spec = resolve_aggregate(a.name, arg.type)
            aggs.append(PlanAggregate(
                spec, len(pre_exprs) + len(agg_inputs) - 1, a.distinct))
        pre_cols = (tuple((f"k{i}", x.type)
                          for i, x in enumerate(pre_exprs))
                    + tuple((f"a{i}", x.type)
                            for i, x in enumerate(agg_inputs)))
        pre = ProjectNode(sub_from.node, tuple(pre_exprs + agg_inputs),
                          pre_cols)
        agg_cols = (tuple(pre_cols[:len(sub_keys)])
                    + tuple((f"agg{i}", a.spec.result_type)
                            for i, a in enumerate(aggs)))
        agg_node = AggregationNode(pre, tuple(range(len(sub_keys))),
                                   tuple(aggs), agg_cols)
        g_fields = [Field(n, None, ty) for n, ty in agg_cols]
        gctx = GroupingContext([], agg_asts, g_fields)
        gctx.group_asts = [None] * len(sub_keys)  # type: ignore[list-item]
        val_tr = Translator(Scope(g_fields), gctx)
        value = val_tr.translate(q.select[0].expr)
        val_cols = agg_cols[:len(sub_keys)] + (("$value", value.type),)
        val_proj = ProjectNode(
            agg_node,
            tuple(B.ref(i, agg_cols[i][1])
                  for i in range(len(sub_keys))) + (value,),
            val_cols)
        return val_proj, value.type, len(sub_keys)

    def _attach_exists_marker(self, rel: RelationPlan, q: t.Query
                              ) -> Tuple[RelationPlan, RowExpression]:
        """EXISTS as a BOOLEAN channel (semiJoinOutput symbol role)."""
        probe = self._try_uncorrelated(q, rel)
        if probe is not None:
            # global count > 0 cross-joined (always exactly one row)
            cnt_spec = resolve_aggregate("count", None)
            agg = AggregationNode(
                probe.node, (), (PlanAggregate(cnt_spec, None),),
                (("$cnt", T.BIGINT),))
            nleft = len(rel.scope.fields)
            cols = rel.node.columns + agg.columns
            joined = JoinNode("cross", rel.node, agg, (), (), cols)
            scope = Scope(rel.scope.fields
                          + [Field("$cnt", "$subquery", T.BIGINT)],
                          rel.scope.parent)
            marker = B.comparison(">", B.ref(nleft, T.BIGINT),
                                  B.const(0, T.BIGINT))
            return RelationPlan(joined, scope), marker
        sub_from, corr_eq, corr_other = self._plan_correlated_from(rel, q)
        if corr_other or not corr_eq:
            raise SqlAnalysisError(
                "EXISTS in this position supports only equality "
                "correlation")
        sub_keys = [ch for ch, _ in corr_eq]
        pre_exprs = tuple(B.ref(ch, sub_from.scope.fields[ch].type)
                          for ch in sub_keys)
        pre_cols = tuple((f"k{i}", x.type)
                         for i, x in enumerate(pre_exprs))
        pre = ProjectNode(sub_from.node, pre_exprs, pre_cols)
        cnt_spec = resolve_aggregate("count", None)
        agg_cols = pre_cols + (("$cnt", T.BIGINT),)
        agg = AggregationNode(pre, tuple(range(len(sub_keys))),
                              (PlanAggregate(cnt_spec, None),), agg_cols)
        src = rel
        tr = Translator(src.scope)
        outer_keys = []
        for _, outer_ast in corr_eq:
            key = tr.translate(outer_ast)
            src, ch = _channel_for(src, key)
            tr = Translator(src.scope)
            outer_keys.append(ch)
        nleft = len(src.scope.fields)
        cols = src.node.columns + agg_cols
        joined = JoinNode("left", src.node, agg, tuple(outer_keys),
                          tuple(range(len(sub_keys))), cols)
        scope = Scope(src.scope.fields
                      + [Field(n, "$subquery", ty) for n, ty in agg_cols],
                      src.scope.parent)
        marker = B.call("is_not_null",
                        B.ref(nleft + len(sub_keys), T.BIGINT))
        return RelationPlan(joined, scope), marker

    def _attach_in_marker(self, rel: RelationPlan, e: t.InSubquery
                          ) -> Tuple[RelationPlan, RowExpression]:
        """``x IN (subquery)`` as a three-valued BOOLEAN channel
        (semiJoinOutput semantics): LEFT JOIN a DISTINCT build on x;
        TRUE on match, UNKNOWN for NULL x or an unmatched x against a
        build containing NULL, else FALSE — so NOT IN under OR negates
        correctly."""
        sub = self._try_uncorrelated(e.query, rel)
        if sub is None:
            raise SqlAnalysisError(
                "correlated IN subquery in this position")
        if len(sub.scope.fields) != 1:
            raise SqlAnalysisError("IN subquery must return one column")
        k_type = sub.scope.fields[0].type
        distinct = AggregationNode(sub.node, (0,), (),
                                   (("$k", k_type),))
        tr = Translator(rel.scope)
        key = tr.translate(e.expr)
        src, ch = _channel_for(rel, key)
        nleft = len(src.scope.fields)
        cols = src.node.columns + distinct.columns
        joined = JoinNode("left", src.node, distinct, (ch,), (0,), cols)
        scope = Scope(src.scope.fields
                      + [Field("$k", "$subquery", k_type)],
                      src.scope.parent)
        rel2 = RelationPlan(joined, scope)
        # build-side NULL presence (one extra global-agg scan of the
        # subquery, cross-joined as a single row)
        sub2 = self._try_uncorrelated(e.query, rel)
        has_null_src = FilterNode(
            sub2.node, B.call("is_null", B.ref(0, k_type)))
        cnt_spec = resolve_aggregate("count", None)
        bhn_agg = AggregationNode(
            has_null_src, (), (PlanAggregate(cnt_spec, None),),
            (("$bhn", T.BIGINT),))
        nleft2 = len(rel2.scope.fields)
        cols2 = rel2.node.columns + bhn_agg.columns
        joined2 = JoinNode("cross", rel2.node, bhn_agg, (), (), cols2)
        scope2 = Scope(rel2.scope.fields
                       + [Field("$bhn", "$subquery", T.BIGINT)],
                       rel2.scope.parent)
        matched = B.call("is_not_null", B.ref(nleft, k_type))
        build_has_null = B.comparison(
            ">", B.ref(nleft2, T.BIGINT), B.const(0, T.BIGINT))
        key_ref = B.ref(ch, key.type)
        # 3VL: NULL x -> UNKNOWN; match -> TRUE; no match w/ build NULL
        # -> UNKNOWN; else FALSE
        marker = B.if_(
            B.call("is_null", key_ref), B.null(T.BOOLEAN),
            B.if_(matched, B.const(True, T.BOOLEAN),
                  B.if_(build_has_null, B.null(T.BOOLEAN),
                        B.const(False, T.BOOLEAN))))
        return RelationPlan(joined2, scope2), marker

    class _FoldedValue:
        """Plan-time-folded VALUES entry (Python-domain value + type)."""

        __slots__ = ("type", "value")

        def __init__(self, typ: T.Type, value):
            self.type = typ
            self.value = value

    def _plan_inline_values(self, r: t.InlineValues,
                            outer: Optional[Scope]) -> RelationPlan:
        """VALUES rows -> ValuesNode (constant folding at plan time; the
        reference's Values/ValuesOperator path)."""
        tr = Translator(Scope([], outer))
        if not r.rows:
            raise SqlAnalysisError("VALUES requires at least one row")
        width = len(r.rows[0])
        consts: List[List[Constant]] = []
        for row in r.rows:
            if len(row) != width:
                raise SqlAnalysisError("VALUES rows differ in width")
            out_row = []
            for e in row:
                rex = tr.translate(e)
                if not isinstance(rex, Constant):
                    # fold input-free expressions (ARRAY[..], row(..),
                    # map(..), date arithmetic) at plan time
                    from presto_tpu.expr.ir import input_channels

                    if input_channels(rex):
                        raise SqlAnalysisError(
                            "VALUES entries must be constant expressions")
                    from presto_tpu.batch import Batch as _B
                    from presto_tpu.expr.compile import evaluate

                    col = evaluate(rex, _B((), 1))
                    out_row.append(self._FoldedValue(rex.type, col.to_pylist(1)[0]))
                    continue
                out_row.append(rex)
            consts.append(out_row)
        cols = []
        for j in range(width):
            ctype = _common_type([consts[i][j].type
                                  for i in range(len(consts))])
            name = (r.column_aliases[j] if j < len(r.column_aliases)
                    else f"_col{j}")
            cols.append((name, ctype))
        py_rows = []
        for row in consts:
            out_row = []
            for c, (_, ctype) in zip(row, cols):
                v = c.value
                if isinstance(c, self._FoldedValue):  # Python-domain value
                    out_row.append(v)
                    continue
                if v is not None and not c.type.is_dictionary:
                    v = c.type.to_python(v)
                if v is not None and ctype.name in ("double", "real") \
                        and not isinstance(v, float):
                    v = float(v)
                out_row.append(v)
            py_rows.append(tuple(out_row))
        node = ValuesNode(tuple(cols), tuple(py_rows))
        fields = [Field(n, r.alias, typ) for n, typ in cols]
        return RelationPlan(node, Scope(fields, outer))

    # --- aggregation -------------------------------------------------------
    def _plan_aggregation(self, rel: RelationPlan, q: t.Query):
        scope = rel.scope
        tr = Translator(scope)
        # group expressions (support ordinals into the select list)
        group_asts: List[t.Expression] = []
        for g in q.group_by:
            if isinstance(g, t.NumberLiteral) and g.text.isdigit():
                item = q.select[int(g.text) - 1]
                group_asts.append(item.expr)
            else:
                group_asts.append(g)
        group_rex = [tr.translate(g) for g in group_asts]

        agg_asts: List[t.FunctionCall] = []
        for item in q.select:
            _collect_aggs(item.expr, agg_asts)
        if q.having is not None:
            _collect_aggs(q.having, agg_asts)
        for s in q.order_by:
            _collect_aggs(s.expr, agg_asts)

        pre_exprs: List[RowExpression] = list(group_rex)
        aggs: List[PlanAggregate] = []
        for a in agg_asts:
            if a.is_star or not a.args:
                spec = resolve_aggregate("count", None)
                aggs.append(PlanAggregate(spec, None, a.distinct))
                continue
            arg = _agg_input(tr, a)
            spec = resolve_aggregate(a.name, arg.type)
            _patch_agg_spec(tr, a, spec)
            aggs.append(PlanAggregate(spec, len(pre_exprs), a.distinct))
            pre_exprs.append(arg)
        if not pre_exprs:  # bare count(*): keep one channel for row counts
            pre_exprs = [B.ref(0, scope.fields[0].type)]
        pre_cols = tuple((f"c{i}", x.type) for i, x in enumerate(pre_exprs))
        pre = ProjectNode(rel.node, tuple(pre_exprs), pre_cols)
        out_cols = (tuple((f"g{i}", x.type)
                          for i, x in enumerate(group_rex))
                    + tuple((f"agg{i}", a.spec.result_type)
                            for i, a in enumerate(aggs)))
        node = AggregationNode(pre, tuple(range(len(group_rex))),
                               tuple(aggs), out_cols)
        out_fields = [Field(n, None, typ) for n, typ in out_cols]
        grouping = GroupingContext(group_asts, agg_asts, out_fields)
        out = RelationPlan(node, Scope(out_fields, scope.parent))
        # HAVING is handled by the caller (it may contain subqueries); the
        # grouped translator resolves via GroupingContext.lookup, so scope
        # names stay synthetic
        return out, grouping

    def _plan_grouping_sets(self, rel: RelationPlan, q: t.Query):
        """GROUPING SETS / ROLLUP / CUBE: one aggregation per set over the
        shared pre-projection, each projected onto the full key schema
        (absent keys as NULL), unioned — the GroupIdOperator role
        (presto-main/.../operator/GroupIdOperator.java:32) expressed as a
        union of grouped aggregations."""
        scope = rel.scope
        tr = Translator(scope)
        group_asts = list(q.group_by)
        group_rex = [tr.translate(g) for g in group_asts]

        agg_asts: List[t.FunctionCall] = []
        for item in q.select:
            _collect_aggs(item.expr, agg_asts)
        if q.having is not None:
            _collect_aggs(q.having, agg_asts)
        for s in q.order_by:
            _collect_aggs(s.expr, agg_asts)

        pre_exprs: List[RowExpression] = list(group_rex)
        aggs: List[PlanAggregate] = []
        for a in agg_asts:
            if a.is_star or not a.args:
                spec = resolve_aggregate("count", None)
                aggs.append(PlanAggregate(spec, None, a.distinct))
                continue
            arg = _agg_input(tr, a)
            spec = resolve_aggregate(a.name, arg.type)
            _patch_agg_spec(tr, a, spec)
            aggs.append(PlanAggregate(spec, len(pre_exprs), a.distinct))
            pre_exprs.append(arg)
        pre_cols = tuple((f"c{i}", x.type) for i, x in enumerate(pre_exprs))
        pre = ProjectNode(rel.node, tuple(pre_exprs), pre_cols)

        key_types = [x.type for x in group_rex]
        out_cols = (tuple((f"g{i}", typ)
                          for i, typ in enumerate(key_types))
                    + tuple((f"agg{i}", a.spec.result_type)
                            for i, a in enumerate(aggs))
                    + (("$grouping_id", T.BIGINT),))
        branches: List[PlanNode] = []
        for subset in q.grouping_sets:
            branch_aggs = tuple(aggs)
            branch_cols = (tuple((f"g{i}", key_types[i]) for i in subset)
                           + tuple((f"agg{i}", a.spec.result_type)
                                   for i, a in enumerate(aggs)))
            if not subset and not aggs:
                # a zero-column aggregation cannot execute; the grand
                # total branch carries a hidden count(*) the projection
                # ignores
                branch_aggs = (PlanAggregate(
                    resolve_aggregate("count", None), None),)
                branch_cols = (("$cnt", T.BIGINT),)
            agg_node = AggregationNode(pre, tuple(subset), branch_aggs,
                                       branch_cols)
            pos = {ch: k for k, ch in enumerate(subset)}
            exprs: List[RowExpression] = []
            for i, typ in enumerate(key_types):
                if i in pos:
                    exprs.append(B.ref(pos[i], typ))
                else:
                    exprs.append(B.null(typ))
            for j, a in enumerate(aggs):
                exprs.append(B.ref(len(subset) + j, a.spec.result_type))
            # grouping-id bitmask for the grouping() function (GroupId
            # operator's groupId symbol): bit i = key i absent here
            gid = sum(1 << i for i in range(len(key_types))
                      if i not in pos)
            exprs.append(B.const(gid, T.BIGINT))
            branches.append(ProjectNode(agg_node, tuple(exprs), out_cols))
        node: PlanNode = (branches[0] if len(branches) == 1
                          else UnionNode(tuple(branches), out_cols))
        out_fields = [Field(n, None, typ) for n, typ in out_cols]
        grouping = GroupingContext(
            group_asts, agg_asts, out_fields,
            grouping_id_channel=len(key_types) + len(aggs))
        return RelationPlan(node, Scope(out_fields, scope.parent)), grouping

    # --- window functions --------------------------------------------------
    _RANKING = {"row_number", "rank", "dense_rank", "percent_rank",
                "cume_dist", "ntile"}
    _VALUE_FNS = {"lag", "lead", "first_value", "last_value", "nth_value"}
    _WINDOW_AGGS = {"sum", "count", "avg", "min", "max"}

    def _plan_windows(self, rel: RelationPlan,
                      calls: List[t.FunctionCall],
                      grouping: Optional[GroupingContext]):
        """Plan WindowNodes (one per distinct partition/order spec) over
        ``rel`` and return (new rel, {window-call AST -> channel ref}).
        The source's channels are preserved as a prefix; each WindowNode
        appends one channel per function."""
        scope = rel.scope
        tr = Translator(scope, grouping)
        node = rel.node
        n_src = len(node.columns)
        pre_exprs: List[RowExpression] = [
            B.ref(i, typ) for i, (_, typ) in enumerate(node.columns)]

        def chan_of(rex: RowExpression) -> int:
            if isinstance(rex, InputRef):
                return rex.index
            for i, e in enumerate(pre_exprs):
                if e == rex:
                    return i
            pre_exprs.append(rex)
            return len(pre_exprs) - 1

        def const_int(e: t.Expression, what: str) -> int:
            rex = tr.translate(e)
            if not isinstance(rex, Constant) or not isinstance(
                    rex.value, (int, float)):
                raise SqlAnalysisError(f"{what} must be a constant")
            return int(rex.value)

        # resolve each call -> (spec key, PlanWindowFunction parts)
        grouped_specs: Dict[Tuple, List[Tuple[t.FunctionCall, dict]]] = {}
        for call in calls:
            w = call.window
            part_channels = tuple(chan_of(tr.translate(p))
                                  for p in w.partition_by)
            order_keys = tuple(
                (chan_of(tr.translate(s.expr)), s.ascending, s.nulls_first)
                for s in w.order_by)
            fn = self._resolve_window_fn(call, tr, chan_of, const_int)
            key = (part_channels, order_keys)
            grouped_specs.setdefault(key, []).append((call, fn))

        if len(pre_exprs) > n_src:
            cols = tuple(node.columns) + tuple(
                (f"$winarg{i}", e.type)
                for i, e in enumerate(pre_exprs[n_src:]))
            node = ProjectNode(node, tuple(pre_exprs), cols)

        win_map: Dict[t.Expression, RowExpression] = {}
        for (part_channels, order_keys), entries in grouped_specs.items():
            base = len(node.columns)
            funcs = tuple(PlanWindowFunction(**fn) for _, fn in entries)
            cols = tuple(node.columns) + tuple(
                (f"$win{base + i}", f.result_type)
                for i, f in enumerate(funcs))
            node = WindowNode(node, part_channels, order_keys, funcs, cols)
            for i, (call, fn) in enumerate(entries):
                win_map[call] = B.ref(base + i, fn["result_type"])

        # scope keeps the original named fields; window channels are
        # addressable only through win_map
        new_scope = Scope(list(scope.fields), scope.parent)
        return RelationPlan(node, new_scope), win_map

    def _resolve_window_fn(self, call: t.FunctionCall, tr: Translator,
                           chan_of, const_int) -> dict:
        name = call.name
        w = call.window
        has_order = bool(w.order_by)
        fn: dict = dict(name=name, arg_channels=(), result_type=T.BIGINT,
                        frame_unit="range",
                        frame_start="unbounded_preceding",
                        frame_end="current" if has_order
                        else "unbounded_following")
        if name in self._RANKING:
            if not has_order and name != "row_number":
                raise SqlAnalysisError(f"{name} requires window ORDER BY")
            if name in ("percent_rank", "cume_dist"):
                fn["result_type"] = T.DOUBLE
            if name == "ntile":
                if len(call.args) != 1:
                    raise SqlAnalysisError("ntile takes one argument")
                fn["offset"] = const_int(call.args[0], "ntile bucket count")
            return fn
        if name in self._VALUE_FNS:
            if not call.args:
                raise SqlAnalysisError(f"{name} requires an argument")
            arg = tr.translate(call.args[0])
            fn["arg_channels"] = (chan_of(arg),)
            fn["result_type"] = arg.type
            if name in ("lag", "lead"):
                fn["offset"] = (const_int(call.args[1], f"{name} offset")
                                if len(call.args) > 1 else 1)
                if len(call.args) > 2:
                    dflt = _coerce(tr.translate(call.args[2]), arg.type)
                    fn["default_channel"] = chan_of(dflt)
            elif name == "nth_value":
                if len(call.args) != 2:
                    raise SqlAnalysisError("nth_value takes two arguments")
                fn["offset"] = const_int(call.args[1], "nth_value position")
            if w.frame is not None:
                self._apply_frame(fn, w.frame, const_int)
            return fn
        if name in self._WINDOW_AGGS:
            if call.is_star or not call.args:
                if name != "count":
                    raise SqlAnalysisError(f"{name} requires an argument")
                fn["result_type"] = T.BIGINT
            else:
                arg = tr.translate(call.args[0])
                fn["arg_channels"] = (chan_of(arg),)
                if name == "count":
                    fn["result_type"] = T.BIGINT
                elif name in ("min", "max"):
                    fn["result_type"] = arg.type
                elif name == "sum":
                    fn["result_type"] = (
                        T.BIGINT if T.is_integral(arg.type)
                        else arg.type)
                else:  # avg
                    fn["result_type"] = (
                        arg.type if isinstance(arg.type, T.DecimalType)
                        else T.DOUBLE)
            if w.frame is not None:
                self._apply_frame(fn, w.frame, const_int)
            return fn
        raise SqlAnalysisError(f"unknown window function {name}")

    @staticmethod
    def _apply_frame(fn: dict, frame: t.WindowFrame, const_int) -> None:
        fn["frame_unit"] = frame.unit

        def bound(b: t.FrameBound, which: str):
            fn[f"frame_{which}"] = b.kind
            if b.kind in ("preceding", "following"):
                fn[f"frame_{which}_offset"] = const_int(
                    b.value, "frame offset")

        bound(frame.start, "start")
        bound(frame.end, "end")
        if frame.unit == "range" and (
                fn["frame_start"] in ("preceding", "following")
                or fn["frame_end"] in ("preceding", "following")):
            raise SqlAnalysisError(
                "RANGE frames with value offsets are not supported")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _collect_windows(e: t.Node, out: List[t.FunctionCall]):
    """Collect windowed FunctionCalls (not descending into subqueries)."""
    if isinstance(e, t.FunctionCall) and e.window is not None:
        if e not in out:
            out.append(e)
        return
    if isinstance(e, (t.InSubquery, t.Exists, t.ScalarSubquery)):
        return
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, t.Node):
            _collect_windows(v, out)
        elif isinstance(v, tuple):
            for item in v:
                if isinstance(item, t.Node):
                    _collect_windows(item, out)
                elif isinstance(item, tuple):
                    for sub in item:
                        if isinstance(sub, t.Node):
                            _collect_windows(sub, out)


_TWO_ARG_AGGS = {"map_agg", "min_by", "max_by", "corr", "covar_samp",
                 "covar_pop", "regr_slope", "regr_intercept",
                 "learn_classifier", "learn_regressor"}


def _agg_input(tr: Translator, a: t.FunctionCall) -> RowExpression:
    """Aggregate input expression; two-argument aggregates pack their
    arguments into a row(...) channel (the planner-side analogue of the
    reference's multi-channel accumulator inputs)."""
    if a.name.lower() in _TWO_ARG_AGGS:
        if len(a.args) != 2:
            raise SqlAnalysisError(f"{a.name} takes two arguments")
        k = tr.translate(a.args[0])
        v = tr.translate(a.args[1])
        fn = F.resolve_row_constructor([k.type, v.type])
        return Call("row", (k, v), fn.result_type, fn)
    return tr.translate(a.args[0])


def _patch_agg_spec(tr: Translator, a: t.FunctionCall, spec) -> None:
    """Constant-parameter aggregates: bake the parameter into finalize
    (approx_percentile's percentile argument)."""
    if a.name.lower() == "approx_percentile" and len(a.args) == 2:
        p = tr.translate(a.args[1])
        if not isinstance(p, Constant) or p.value is None:
            raise SqlAnalysisError(
                "approx_percentile(x, p) requires constant p")
        spec.finalize = f"approx_percentile:{float(p.value)}"


def _collect_aggs(e: t.Node, out: List[t.FunctionCall]):
    if (isinstance(e, t.FunctionCall) and e.name in AGG_NAMES
            and e.window is None):
        if e not in out:
            out.append(e)
        return
    if isinstance(e, (t.InSubquery, t.Exists, t.ScalarSubquery)):
        return
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, t.Node):
            _collect_aggs(v, out)
        elif isinstance(v, tuple):
            for item in v:
                if isinstance(item, t.Node):
                    _collect_aggs(item, out)
                elif isinstance(item, tuple):
                    for sub in item:
                        if isinstance(sub, t.Node):
                            _collect_aggs(sub, out)


def _conjunct_side(c: t.Expression, lscope: Scope, rscope: Scope) -> str:
    sides = set()
    for ident in _identifiers(c):
        if lscope.try_resolve(ident.parts) is not None:
            sides.add("left")
        elif rscope.try_resolve(ident.parts) is not None:
            sides.add("right")
        else:
            raise SqlAnalysisError(f"column {ident} cannot be resolved "
                                   "in join condition")
    if sides == {"left"}:
        return "left"
    if sides == {"right"}:
        return "right"
    return "both"


def _try_translate_side(e: t.Expression, scope: Scope) -> Optional[int]:
    """Channel index if e is a bare column of this scope, else None."""
    if isinstance(e, t.Identifier):
        return scope.try_resolve(e.parts)
    return None


def _channel_for(rel: RelationPlan, key: RowExpression):
    """Ensure ``key`` is available as a bare channel, appending a
    projection when it is computed."""
    if isinstance(key, InputRef):
        return rel, key.index
    n = len(rel.node.columns)
    exprs = tuple(B.ref(i, typ) for i, (_, typ) in
                  enumerate(rel.node.columns)) + (key,)
    cols = rel.node.columns + (("$key", key.type),)
    node = ProjectNode(rel.node, exprs, cols)
    scope = Scope(rel.scope.fields + [Field("$key", None, key.type)],
                  rel.scope.parent)
    return RelationPlan(node, scope), n


def _and_all(exprs: List[RowExpression]) -> RowExpression:
    out = exprs[0]
    for e in exprs[1:]:
        out = B.and_(out, e)
    return out


def _derive_name(e: t.Expression, idx: int) -> str:
    if isinstance(e, t.Identifier):
        return e.parts[-1]
    if isinstance(e, t.FunctionCall):
        return e.name
    return f"_col{idx}"
