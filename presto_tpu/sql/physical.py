"""Physical planner: logical PlanNode tree -> executable Pipelines.

The LocalExecutionPlanner analogue (presto-main/.../sql/planner/
LocalExecutionPlanner.java:291): a bottom-up visitor mapping each PlanNode
to OperatorFactory chains, breaking pipelines at join build sides exactly
where the reference's LookupSourceFactory rendezvous sits (build pipelines
are emitted before the pipeline that probes them, matching
execute_pipelines' sequential contract).

Aggregate decomposition happens here: a PlanAggregate's AggSpec components
become primitive AggChannels (sum/count/min/max; sumsq pre-projects x*x)
and ``finalize`` becomes a post-aggregation projection (avg = sum/count,
stddev/variance from the moment components) — the role the reference's
AccumulatorCompiler + partial/final Step split plays.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.config import DEFAULT, EngineConfig
from presto_tpu.connectors.api import ConnectorRegistry, Split
from presto_tpu.exec.aggregation import (
    AggChannel, GlobalAggregationOperatorFactory,
    HashAggregationOperatorFactory,
)
from presto_tpu.exec.driver import Pipeline
from presto_tpu.exec.joinop import (
    HashBuildOperatorFactory, LookupJoinOperatorFactory,
)
from presto_tpu.exec.nestedloop import (
    EnforceSingleRowOperatorFactory, NestedLoopBuildOperatorFactory,
    NestedLoopJoinOperatorFactory,
)
from presto_tpu.exec.operators import (
    FilterProjectOperatorFactory, LimitOperatorFactory,
    OutputCollectorFactory, TableScanOperatorFactory, ValuesOperatorFactory,
)
from presto_tpu.exec.sortop import OrderByOperatorFactory, SortSpec
from presto_tpu.exec.unionop import (
    UnionBuffer, UnionSinkOperatorFactory, UnionSourceOperatorFactory,
)
from presto_tpu.exec.windowop import WindowOperatorFactory
from presto_tpu.expr import build as B
from presto_tpu.expr.ir import InputRef, RowExpression
from presto_tpu.sql.plan import (
    AggregationNode, EnforceSingleRowNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanAggregate, PlanNode, ProjectNode, RemoteMergeNode,
    RemoteSourceNode, SemiJoinNode, SortNode, TableFinishNode,
    TableScanNode, TableWriterNode, UnionNode,
    UnnestNode, ValuesNode, WindowNode,
)


# process-wide count of physical plans built (PhysicalPlanner.plan
# calls) — the plan-cache physical-factory sharing pin: the SECOND
# execution of a cached statement must not bump it
PLANS_BUILT = 0

# process-wide count of worker-side fragment lowerings
# (PhysicalPlanner.plan_fragment calls) — the worker plan_fragment
# cache pin: repeat task creates of a cached statement must not bump it
FRAGMENTS_LOWERED = 0


@dataclasses.dataclass
class PhysicalPlan:
    pipelines: List[Pipeline]
    collector: OutputCollectorFactory
    column_names: List[str]
    column_types: List[T.Type]

    def reset_for_execution(self) -> None:
        """Re-arm every factory's cross-execution state (collector
        batches, union buffers, build rendezvous) so the SAME operator
        factory chains execute again — what lets the plan cache share
        the physical-planner output across repeat statements instead of
        re-planning per execution."""
        for p in self.pipelines:
            for f in p.factories:
                f.reset_for_execution()


class PhysicalPlanner:
    def __init__(self, registry: ConnectorRegistry,
                 config: EngineConfig = DEFAULT,
                 scan_shard: Optional[Tuple[int, int]] = None,
                 remote_sources: Optional[dict] = None,
                 fetch_headers: Optional[dict] = None,
                 http_client=None, task_id: Optional[str] = None,
                 exchange_register=None,
                 trace_token: Optional[str] = None,
                 spool=None):
        """``scan_shard=(task_index, task_count)`` makes scans generate only
        this task's deterministic share of splits (distributed source
        stages, P5); ``remote_sources`` maps fragment id -> producer buffer
        URLs for RemoteSourceNode lowering.  ``http_client`` (a
        RetryingHttpClient) carries the node's error-tracking/backoff
        policy into exchange fetches; ``task_id`` labels their failures;
        ``exchange_register`` receives each created ExchangeClient so the
        owning task can repoint remote sources (mid-query recovery)."""
        self.registry = registry
        self.config = config
        self.scan_shard = scan_shard
        self.remote_sources = remote_sources or {}
        # intra-cluster auth headers for exchange fetches (per cluster,
        # not process-global: one process may host several clusters)
        self.fetch_headers = fetch_headers or {}
        self.http_client = http_client
        self.task_id = task_id
        self.trace_token = trace_token
        self.exchange_register = exchange_register
        # shared SpoolStore for spool:// remote-source locations (the
        # spooled exchange tier); None when spooling is disabled
        self.spool = spool
        self._done_pipelines: List[Pipeline] = []
        self._counter = 0

    def plan(self, root: OutputNode) -> PhysicalPlan:
        global PLANS_BUILT
        PLANS_BUILT += 1
        factories, splits = self._lower(root.source)
        collector = OutputCollectorFactory()
        factories.append(collector)
        self._done_pipelines.append(
            Pipeline(factories, splits, name="output"))
        self._fuse()
        return PhysicalPlan(self._done_pipelines, collector,
                            [n for n, _ in root.columns],
                            [t for _, t in root.columns])

    def plan_fragment(self, root: PlanNode,
                      sink_factory) -> List[Pipeline]:
        """Lower a fragment root and terminate it with the given output
        sink (PartitionedOutput/TaskOutput) — the worker-task entry."""
        global FRAGMENTS_LOWERED
        FRAGMENTS_LOWERED += 1
        factories, splits = self._lower(root)
        factories.append(sink_factory)
        self._done_pipelines.append(
            Pipeline(factories, splits, name="fragment"))
        self._fuse()
        return self._done_pipelines

    def _fuse(self) -> None:
        """Pipeline-fusion post-pass (exec/fusion.py): rewrite each
        lowered chain's runs of row-local operators into fused segment
        programs.  Runs after every lowering decision that inspects the
        raw chains (streaming-agg eligibility, grouped execution,
        dynamic-filter placement)."""
        if not getattr(self.config, "pipeline_fusion", False):
            return
        from presto_tpu.exec.fusion import fuse_pipelines

        fuse_pipelines(self._done_pipelines, self.config)

    # -- lowering -----------------------------------------------------------
    def _lower(self, node: PlanNode):
        """Returns (operator factory chain, splits) producing node's
        output batches; build-side pipelines are appended to
        self._done_pipelines in dependency order."""
        if isinstance(node, TableScanNode):
            conn = self.registry.get(node.catalog)
            handle = conn.get_table(node.table)
            if self.scan_shard is None:
                # enough splits to feed task_concurrency drivers through
                # the LocalExchange tier (4x for balance, the reference's
                # split-batch shape)
                desired = (max(4 * self.config.task_concurrency, 4)
                           if self.config.task_concurrency > 1 else 1)
                splits = conn.get_splits(handle, desired)
            else:
                # deterministic split-modulo placement: every task of a
                # source stage generates the full split list and keeps its
                # residue class (the SourcePartitionedScheduler role
                # without central placement)
                idx, count = self.scan_shard
                all_splits = conn.get_splits(handle, max(count * 4, 4))
                splits = all_splits[idx::count]
            return ([TableScanOperatorFactory(
                conn, node.column_names,
                batch_rows=self.config.scan_batch_rows,
                table=node.table)], splits)
        if isinstance(node, RemoteSourceNode):
            from presto_tpu.server.exchangeop import ExchangeOperatorFactory

            locations: List[str] = []
            for fid in node.fragment_ids:
                locations.extend(self.remote_sources.get(fid, ()))
            fac = ExchangeOperatorFactory(
                locations, headers=self.fetch_headers,
                http=self.http_client, task_id=self.task_id,
                trace_token=self.trace_token, spool=self.spool,
                spool_stall_s=self.config.exchange_spool_stall_s)
            # producer fragment ids, so the worker plan_fragment cache
            # can rebind this factory's locations per task create
            fac.source_fragment_ids = tuple(node.fragment_ids)
            if self.exchange_register is not None:
                self.exchange_register(fac)
            return ([fac], [])
        if isinstance(node, RemoteMergeNode):
            from presto_tpu.server.exchangeop import (
                MergeExchangeOperatorFactory,
            )

            locations = []
            for fid in node.fragment_ids:
                locations.extend(self.remote_sources.get(fid, ()))
            fac = MergeExchangeOperatorFactory(
                locations, node.sort_keys,
                [t for _, t in node.columns], node.limit,
                headers=self.fetch_headers, http=self.http_client,
                task_id=self.task_id, trace_token=self.trace_token,
                spool=self.spool,
                spool_stall_s=self.config.exchange_spool_stall_s)
            fac.source_fragment_ids = tuple(node.fragment_ids)
            if self.exchange_register is not None:
                self.exchange_register(fac)
            return ([fac], [])
        if isinstance(node, ValuesNode):
            from presto_tpu.batch import batch_from_pylist

            batch = batch_from_pylist(node.types, list(node.rows))
            return ([ValuesOperatorFactory([batch.to_device()])], [])
        if isinstance(node, (FilterNode, ProjectNode)):
            return self._lower_filter_project(node)
        if isinstance(node, AggregationNode):
            return self._lower_aggregation(node)
        if isinstance(node, JoinNode):
            return self._lower_join(node)
        if isinstance(node, SemiJoinNode):
            return self._lower_semijoin(node)
        if isinstance(node, SortNode):
            chain, splits = self._lower(node.source)
            specs = [SortSpec(c, not asc, bool(nf))
                     for c, asc, nf in node.sort_keys]
            chain.append(OrderByOperatorFactory(specs))
            return chain, splits
        if isinstance(node, LimitNode):
            if isinstance(node.source, SortNode):
                # TopN fusion (TopNOperator.java:35 role): sort + limit
                # becomes one truncated sort-permutation kernel
                chain, splits = self._lower(node.source.source)
                specs = [SortSpec(c, not asc, bool(nf))
                         for c, asc, nf in node.source.sort_keys]
                chain.append(OrderByOperatorFactory(specs, node.count))
                return chain, splits
            chain, splits = self._lower(node.source)
            chain.append(LimitOperatorFactory(node.count))
            return chain, splits
        if isinstance(node, EnforceSingleRowNode):
            chain, splits = self._lower(node.source)
            chain.append(EnforceSingleRowOperatorFactory(node.types))
            return chain, splits
        if isinstance(node, WindowNode):
            chain, splits = self._lower(node.source)
            chain.append(WindowOperatorFactory(
                node.partition_channels, node.order_keys, node.functions))
            return chain, splits
        if isinstance(node, UnnestNode):
            from presto_tpu.exec.unnestop import UnnestOperatorFactory

            chain, splits = self._lower(node.source)
            chain.append(UnnestOperatorFactory(
                node.replicate_channels, node.unnest_channels,
                node.ordinality, node.outer))
            return chain, splits
        if isinstance(node, TableWriterNode):
            from presto_tpu.exec.operators import (
                DistributedTableWriterOperatorFactory,
            )

            chain, splits = self._lower(node.source)
            task_tag = (str(self.scan_shard[0])
                        if self.scan_shard is not None else "0")
            chain.append(DistributedTableWriterOperatorFactory(
                self.registry, node.catalog, node.table, node.write_id,
                task_tag))
            return chain, splits
        if isinstance(node, TableFinishNode):
            from presto_tpu.exec.operators import TableFinishOperatorFactory

            chain, splits = self._lower(node.source)
            chain.append(TableFinishOperatorFactory(
                self.registry, node.catalog, node.table, node.write_id))
            return chain, splits
        if isinstance(node, UnionNode):
            buffer = UnionBuffer(len(node.inputs))
            for inp in node.inputs:
                in_chain, in_splits = self._lower(inp)
                in_chain.append(UnionSinkOperatorFactory(buffer))
                self._done_pipelines.append(
                    Pipeline(in_chain, in_splits,
                             name=self._name("union")))
            return [UnionSourceOperatorFactory(buffer)], []
        raise NotImplementedError(
            f"physical lowering for {type(node).__name__}")

    def _lower_filter_project(self, node: PlanNode):
        """Fuse adjacent Filter/Project chains into one PageProcessor-style
        operator (ScanFilterAndProjectOperator fusion)."""
        filters: List[RowExpression] = []
        projections: Optional[Tuple[RowExpression, ...]] = None
        cur = node
        # walk down: Project over (Filter*) — compose
        if isinstance(cur, ProjectNode):
            projections = cur.expressions
            cur = cur.source
        while isinstance(cur, FilterNode):
            filters.append(cur.predicate)
            cur = cur.source
        if (filters and isinstance(cur, WindowNode)
                and len(cur.functions) == 1
                and cur.functions[0].name == "row_number"):
            # TopNRowNumber fusion (TopNRowNumberOperator.java:38): a
            # row_number <= N conjunct becomes a per-partition truncation
            # inside the window sort; filtered rows never materialize
            rn_ch = len(cur.source.columns)
            limit, rest = _extract_rn_limit(filters, rn_ch)
            if limit is not None:
                from presto_tpu.exec.windowop import (
                    TopNRowNumberOperatorFactory,
                )

                chain, splits = self._lower(cur.source)
                chain.append(TopNRowNumberOperatorFactory(
                    cur.partition_channels, cur.order_keys, limit,
                    cur.columns[rn_ch][1]))
                input_types = [t for _, t in cur.columns]
                filt = None
                if rest:
                    filt = rest[-1]
                    for f in reversed(rest[:-1]):
                        filt = B.and_(filt, f)
                if projections is None:
                    projections = tuple(InputRef(i, t)
                                        for i, t in enumerate(input_types))
                chain.append(FilterProjectOperatorFactory(
                    filt, list(projections), input_types))
                return chain, splits
        if (filters and isinstance(cur, JoinNode) and cur.kind == "cross"
                and not cur.left_keys):
            spatial = _extract_spatial(filters, len(cur.left.columns))
            if spatial is not None:
                return self._lower_spatial_join(cur, spatial, projections)
        chain, splits = self._lower(cur)
        input_types = [t for _, t in cur.columns]
        if filters and isinstance(cur, TableScanNode) and splits:
            # filter-pushdown negotiation: offer TupleDomain-lite
            # conjuncts to the connector so it can drop whole splits
            # (HivePartitionManager partition-pruning role); the full
            # filter still runs on surviving rows below
            cons = _extract_constraints(filters, cur.column_names)
            if cons:
                conn = self.registry.get(cur.catalog)
                splits = conn.prune_splits(
                    conn.get_table(cur.table), splits, cons)
        filt = None
        if filters:
            filt = filters[-1]
            for f in reversed(filters[:-1]):
                filt = B.and_(filt, f)
        if projections is None:
            projections = tuple(InputRef(i, t)
                                for i, t in enumerate(input_types))
        chain.append(FilterProjectOperatorFactory(
            filt, list(projections), input_types))
        return chain, splits

    def _lower_aggregation(self, node: AggregationNode):
        if node.step == "final":
            return self._lower_final_aggregation(node)
        chain, splits = self._lower(node.source)
        input_types = [t for _, t in node.source.columns]

        pre_exprs, agg_channels, finalize_specs = decompose_aggregates(
            node.aggregates, input_types)

        needs_pre = len(pre_exprs) > len(input_types)
        if needs_pre:
            pre_types = [e.type for e in pre_exprs]
            chain.append(FilterProjectOperatorFactory(
                None, pre_exprs, input_types))
            input_types = pre_types

        ngroups = len(node.group_channels)
        if ngroups:
            if self._streaming_eligible(chain, node.group_channels,
                                        agg_channels, input_types):
                from presto_tpu.exec.streamagg import (
                    StreamingAggregationOperatorFactory,
                )

                chain.append(StreamingAggregationOperatorFactory(
                    list(node.group_channels), agg_channels, input_types))
            else:
                agg_fac = HashAggregationOperatorFactory(
                    list(node.group_channels), agg_channels, input_types)
                agg_fac.step = node.step
                agg_fac.prereduce_ratio_hint = self._group_ratio_hint(
                    node)
                chain.append(agg_fac)
        else:
            agg_fac = GlobalAggregationOperatorFactory(
                agg_channels, input_types)
            agg_fac.step = node.step
            chain.append(agg_fac)

        if node.step == "partial":
            # distributed PARTIAL: emit raw component columns (keys first);
            # the FINAL stage merges them (HashAggregationOperator.Step:61)
            return chain, splits

        # finalize projection: [keys..., finalized aggs...]
        key_types = [input_types[c] for c in node.group_channels]
        post_in = key_types + [a.out_type for a in agg_channels]
        exprs: List[RowExpression] = [InputRef(i, t)
                                      for i, t in enumerate(key_types)]
        for agg, comps in finalize_specs:
            base = [InputRef(ngroups + c, agg_channels[c].out_type)
                    for c in comps]
            exprs.append(_finalize(agg, base))
        if (len(exprs) != len(post_in)
                or any(not isinstance(e, InputRef) or e.index != i
                       for i, e in enumerate(exprs))):
            chain.append(FilterProjectOperatorFactory(
                None, exprs, post_in))
        return chain, splits

    def _group_ratio_hint(self, node: AggregationNode) -> Optional[float]:
        """Estimated groups/rows ratio for this aggregation (the
        plan-time half of the cost-based pre-reduce decision): derived
        through the same stats tier the memo's cost model uses
        (sql/stats.py NDV propagation).  None when unknown — the fusion
        pass then decides from the runtime observed ratio alone."""
        if not getattr(self.config, "prereduce_cost_based", False):
            return None
        try:
            import types as _pytypes

            from presto_tpu.sql.stats import StatsCalculator

            sc = StatsCalculator(
                _pytypes.SimpleNamespace(registry=self.registry))
            src = sc.stats(node.source)
            ag = sc.stats(node)
            if (src.row_count and ag.row_count is not None
                    and src.row_count > 0):
                return float(ag.row_count) / float(src.row_count)
        except Exception:  # noqa: BLE001 - stats must never fail a plan
            return None
        return None

    def _streaming_eligible(self, chain, group_channels,
                            agg_channels, input_types) -> bool:
        """True when the group keys trace to a PREFIX of the scan's
        declared sort order (rows arrive clustered by the keys), so the
        sort-free streaming aggregation applies
        (StreamingAggregationOperator.java:38; eligibility is the
        reference's LocalProperties/StreamPropertyDerivations check)."""
        if not self.config.streaming_aggregation_enabled:
            return False
        for ch in agg_channels:
            if ch.prim not in ("sum", "count", "min", "max"):
                return False
            if (ch.prim in ("min", "max") and ch.channel is not None
                    and input_types[ch.channel].is_dictionary):
                # the carry merge would compare interning codes
                return False
        from presto_tpu.exec.grouped import scan_column_for_channel

        traced = []
        scan = None
        for g in group_channels:
            hit = scan_column_for_channel(chain, g)
            if hit is None:
                return False
            f, col = hit
            if scan is None:
                scan = f
            elif scan is not f:
                return False
            traced.append(col)
        if scan is None:
            return False
        order = scan.connector.sort_order(
            scan.connector.get_table(scan.table))
        k = len(traced)
        return bool(order) and set(traced) == set(order[:k])

    # merge prim for each partial component prim (steps.py uses the same
    # table for the SPMD in-program exchange variant)
    _FINAL_PRIM = {"count": "sum", "sum": "sum", "min": "min", "max": "max",
                   "collect": "collect_merge",  # partial arrays flatten
                   "sumln": "sum", "sumhash": "sum",
                   "hll": "hll_merge",          # partial sketches max-merge
                   "kll": "kll_merge"}          # quantile sketch union

    def _lower_final_aggregation(self, node: AggregationNode):
        """FINAL step over a partial's output: [keys..., comp0, comp1, ...].
        Re-aggregates each component with its merge primitive, then runs the
        single-step finalize projection."""
        chain, splits = self._lower(node.source)
        input_types = [t for _, t in node.source.columns]
        ngroups = len(node.group_channels)
        agg_channels, finalize_specs = merge_agg_channels(
            node.aggregates, ngroups)

        if ngroups:
            agg_fac = HashAggregationOperatorFactory(
                list(node.group_channels), agg_channels, input_types)
        else:
            agg_fac = GlobalAggregationOperatorFactory(
                agg_channels, input_types)
        agg_fac.step = "final"
        chain.append(agg_fac)

        key_types = [input_types[c] for c in node.group_channels]
        post_in = key_types + [a.out_type for a in agg_channels]
        exprs: List[RowExpression] = [InputRef(i, t)
                                      for i, t in enumerate(key_types)]
        for agg, comps in finalize_specs:
            base = [InputRef(ngroups + c, agg_channels[c].out_type)
                    for c in comps]
            exprs.append(_finalize(agg, base))
        if (len(exprs) != len(post_in)
                or any(not isinstance(e, InputRef) or e.index != i
                       for i, e in enumerate(exprs))):
            chain.append(FilterProjectOperatorFactory(None, exprs, post_in))
        return chain, splits

    def _insert_dynamic_filter(self, chain: List, dyn,
                               key_channels: List[int]) -> None:
        """Place the runtime filter as close to the scan as channel
        provenance allows (the reference pushes dynamic filters into the
        probe-side TableScan, LocalDynamicFilter.java:45): walk backwards
        over FilterProject stages remapping key channels through pure
        InputRef projections, stopping at any operator that changes row
        identity."""
        from presto_tpu.exec.dynamicfilter import (
            DynamicFilterOperatorFactory,
        )

        pos = len(chain)
        keys = list(key_channels)
        i = len(chain) - 1
        while i >= 0:
            f = chain[i]
            if isinstance(f, FilterProjectOperatorFactory):
                mapped = []
                for k in keys:
                    p = f.projections[k] if k < len(f.projections) else None
                    if isinstance(p, InputRef):
                        mapped.append(p.index)
                    else:
                        mapped = None
                        break
                if mapped is None:
                    break
                keys = mapped
                pos = i
                i -= 1
                continue
            break
        chain.insert(pos, DynamicFilterOperatorFactory(dyn, keys))

    def _lower_join(self, node: JoinNode):
        if node.kind == "cross":
            build_chain, build_splits = self._lower(node.right)
            build = NestedLoopBuildOperatorFactory(
                [t for _, t in node.right.columns])
            build_chain.append(build)
            self._done_pipelines.append(
                Pipeline(build_chain, build_splits,
                         name=self._name("xbuild")))
            chain, splits = self._lower(node.left)
            chain.append(NestedLoopJoinOperatorFactory(build))
            return chain, splits
        if node.kind in ("inner", "left"):
            # sides are lowered ONCE; the grouped-execution attempt and
            # the standard path share the chains (re-lowering would
            # duplicate nested build pipelines)
            build_chain, build_splits = self._lower(node.right)
            chain, splits = self._lower(node.left)
            grouped = self._try_grouped_join(node, chain, build_chain)
            if grouped is not None:
                return grouped
            dyn = None
            if node.kind == "inner" and self.config.dynamic_filtering_enabled:
                from presto_tpu.exec.dynamicfilter import DynamicFilter

                dyn = DynamicFilter(len(node.right_keys))
            build = HashBuildOperatorFactory(
                list(node.right_keys), [t for _, t in node.right.columns],
                dynamic_filter=dyn)
            build_chain.append(build)
            self._done_pipelines.append(
                Pipeline(build_chain, build_splits,
                         name=self._name("build")))
            if dyn is not None:
                self._insert_dynamic_filter(chain, dyn,
                                            list(node.left_keys))
            chain.append(LookupJoinOperatorFactory(
                build, list(node.left_keys),
                [t for _, t in node.left.columns],
                join_type=node.kind,
                expansion=self.config.join_expansion_factor))
            if node.residual is not None:
                if node.kind != "inner":
                    raise NotImplementedError(
                        "left-join residual not supported")
                types = [t for _, t in node.columns]
                proj = [InputRef(i, t) for i, t in enumerate(types)]
                chain.append(FilterProjectOperatorFactory(
                    node.residual, proj, types))
            return chain, splits
        raise NotImplementedError(f"{node.kind} join")

    def _lower_spatial_join(self, node: JoinNode, spatial, projections):
        """Filter(ST_pred)(cross join) -> grid-indexed spatial join
        (SpatialJoinOperator.java:42 role): the right side becomes the
        indexed build, candidates come from grid cells, and only they
        run the exact predicate — no cartesian product."""
        from presto_tpu.exec.spatialjoin import SpatialJoinOperatorFactory

        kind, flip, build_expr, probe_expr, radius, rest = spatial
        strict = False
        if isinstance(radius, tuple):
            radius, strict = radius
        build_chain, build_splits = self._lower(node.right)
        build = NestedLoopBuildOperatorFactory(
            [t for _, t in node.right.columns])
        build_chain.append(build)
        self._done_pipelines.append(
            Pipeline(build_chain, build_splits,
                     name=self._name("spatialbuild")))
        chain, splits = self._lower(node.left)
        if flip:
            # the probe side is the container: the operator's exact
            # check swaps operand roles via the 'within' kind
            kind = {"contains": "within"}.get(kind, kind)
        chain.append(SpatialJoinOperatorFactory(
            build, build_expr, probe_expr, kind, radius,
            strict=strict))
        types = [t for _, t in node.columns]
        filt = None
        if rest:
            filt = rest[-1]
            for f in reversed(rest[:-1]):
                filt = B.and_(filt, f)
        if projections is None:
            projections = tuple(InputRef(i, t)
                                for i, t in enumerate(types))
        chain.append(FilterProjectOperatorFactory(
            filt, list(projections), types))
        return chain, splits

    def _try_grouped_join(self, node: JoinNode, probe_chain,
                          build_chain):
        """Grouped execution (P9, Lifespan.java:26-38): when both join
        sides scan tables the connector co-buckets on the join key, run
        the join bucket-sequentially so only 1/k of the build side is
        resident.  Returns the (chain, splits) lowering or None when the
        shape does not qualify (caller falls through to the standard
        lowering, reusing the same chains)."""
        k = self.config.grouped_execution_buckets
        if k <= 1 or len(node.left_keys) != 1 or node.residual is not None:
            return None
        if self.scan_shard is not None:
            # distributed source stage: every task would run ALL buckets
            # over the full table and duplicate the join output — bucket
            # lifespans currently apply to single-task lowering only
            return None
        from presto_tpu.exec.grouped import (
            GroupedJoinSourceOperatorFactory, scan_column_for_channel,
        )

        probe_col = scan_column_for_channel(probe_chain, node.left_keys[0])
        build_col = scan_column_for_channel(build_chain,
                                            node.right_keys[0])
        if probe_col is None or build_col is None:
            # a side is not a pure scan chain (exchange, nested join...)
            return None
        (pscan, pname), (bscan, bname) = probe_col, build_col
        pb = pscan.connector.bucket_splits(
            pscan.connector.get_table(_scan_table(pscan)), pname, k)
        bb = bscan.connector.bucket_splits(
            bscan.connector.get_table(_scan_table(bscan)), bname, k)
        if pb is None or bb is None or pb[0] != bb[0]:
            # not bucketable, or the key domains differ (no co-partition)
            return None
        buckets = []
        for b in range(k):
            build = HashBuildOperatorFactory(
                list(node.right_keys), [t for _, t in node.right.columns])
            bfs = list(build_chain) + [build]
            pfs = list(probe_chain) + [LookupJoinOperatorFactory(
                build, list(node.left_keys),
                [t for _, t in node.left.columns],
                join_type=node.kind,
                expansion=self.config.join_expansion_factor)]
            buckets.append((bfs, bb[1][b], pfs, pb[1][b]))
        return [GroupedJoinSourceOperatorFactory(buckets)], []

    def _lower_semijoin(self, node: SemiJoinNode):
        dyn = None
        if not node.negated and self.config.dynamic_filtering_enabled:
            from presto_tpu.exec.dynamicfilter import DynamicFilter

            dyn = DynamicFilter(len(node.filtering_keys))
        build_chain, build_splits = self._lower(node.filtering)
        build = HashBuildOperatorFactory(
            list(node.filtering_keys),
            [t for _, t in node.filtering.columns],
            dynamic_filter=dyn,
            # a spilled (grace) build loses the global has-null/emptiness
            # facts a null-aware NOT IN needs; keep it resident
            allow_spill=not (node.negated and node.null_aware))
        build_chain.append(build)
        self._done_pipelines.append(
            Pipeline(build_chain, build_splits, name=self._name("sbuild")))
        chain, splits = self._lower(node.source)
        if dyn is not None:
            self._insert_dynamic_filter(chain, dyn,
                                        list(node.source_keys))
        chain.append(LookupJoinOperatorFactory(
            build, list(node.source_keys),
            [t for _, t in node.source.columns],
            join_type="anti" if node.negated else "semi",
            expansion=self.config.join_expansion_factor,
            residual=node.residual,
            null_aware=node.null_aware))
        return chain, splits

    def _name(self, prefix: str) -> str:
        self._counter += 1
        return f"{prefix}{self._counter}"


def _extract_spatial(filters, nleft: int):
    """Find one spatial conjunct over a cross join whose two geometry
    arguments come from opposite sides: ST_Contains/ST_Intersects(a, b)
    or ST_Distance(a, b) <= r.  Returns (kind, flip, build_expr,
    probe_expr, radius, remaining conjuncts) or None; expressions are
    remapped into their side's own channel space."""
    from presto_tpu.expr.ir import Call, Constant, input_channels
    from presto_tpu.sql.optimizer import remap, split_and

    conjuncts = []
    for f in filters:
        conjuncts.extend(split_and(f))

    def sides(expr):
        chans = input_channels(expr)
        if not chans:
            return None
        if all(ch < nleft for ch in chans):
            return "left"
        if all(ch >= nleft for ch in chans):
            return "right"
        return None

    def split_args(a, b):
        sa, sb = sides(a), sides(b)
        if sa == "left" and sb == "right":
            return a, b, False   # probe_expr=a(left), build=b(right)
        if sa == "right" and sb == "left":
            return b, a, True
        return None

    found = None
    rest = []
    for c in conjuncts:
        if found is None and isinstance(c, Call):
            if c.name in ("st_contains", "st_intersects") \
                    and len(c.args) == 2:
                hit = split_args(c.args[0], c.args[1])
                if hit is not None:
                    probe_e, build_e, arg0_is_right = hit
                    kind = ("intersects" if c.name == "st_intersects"
                            else "contains")
                    # contains(A, B): A is the container; flip when the
                    # container argument came from the LEFT (probe) side
                    flip = (kind == "contains") and not arg0_is_right
                    found = (kind, flip, build_e, probe_e, None)
                    continue
            if c.name in ("le", "lt", "ge", "gt") and len(c.args) == 2:
                a, b = c.args
                op = c.name
                if isinstance(a, Constant):
                    a, b = b, a
                    op = {"lt": "gt", "le": "ge",
                          "gt": "lt", "ge": "le"}[op]
                if (isinstance(a, Call) and a.name == "st_distance"
                        and op in ("le", "lt") and isinstance(b, Constant)
                        and isinstance(b.value, (int, float))):
                    hit = split_args(a.args[0], a.args[1])
                    if hit is not None:
                        probe_e, build_e, _ = hit
                        found = ("distance", False, build_e, probe_e,
                                 (float(b.value), op == "lt"))
                        continue
        rest.append(c)
    if found is None:
        return None
    kind, flip, build_e, probe_e, radius = found
    build_e = remap(build_e, {ch: ch - nleft
                              for ch in input_channels(build_e)})
    return kind, flip, build_e, probe_e, radius, rest


def _extract_rn_limit(filters, rn_channel: int):
    """Find one ``row_number <= K`` upper bound among the filter
    conjuncts; returns (K | None, remaining conjuncts)."""
    from presto_tpu.expr.ir import Call, Constant, InputRef
    from presto_tpu.sql.optimizer import split_and

    conjuncts = []
    for f in filters:
        conjuncts.extend(split_and(f))
    limit = None
    rest = []
    for c in conjuncts:
        k = None
        if (limit is None and isinstance(c, Call)
                and c.name in ("le", "lt", "eq", "ge", "gt")
                and len(c.args) == 2):
            a, b = c.args
            op = c.name
            if isinstance(b, InputRef) and isinstance(a, Constant):
                a, b = b, a
                op = {"lt": "gt", "le": "ge",
                      "gt": "lt", "ge": "le"}.get(op, op)
            if (isinstance(a, InputRef) and a.index == rn_channel
                    and isinstance(b, Constant)
                    and isinstance(b.value, int)):
                if op == "le":
                    k = b.value
                elif op == "lt":
                    k = b.value - 1
                elif op == "eq" and b.value == 1:
                    k = 1
        if k is not None and k >= 1:
            # the per-partition truncation IS the bound (le/lt/eq-1 all
            # keep exactly rows with rn <= k)
            limit = k
            continue
        rest.append(c)
    return limit, rest


def _scan_table(scan_factory) -> str:
    """Table name a TableScanOperatorFactory reads (for bucket lookup);
    scans keep a handle-producing connector but not the name directly,
    so it rides on the factory (set at construction)."""
    return scan_factory.table


def _coerce_to(expr: RowExpression, typ: T.Type) -> RowExpression:
    if expr.type == typ:
        return expr
    return B.cast(expr, typ)


def decompose_aggregates(aggregates: Sequence[PlanAggregate],
                         input_types: Sequence[T.Type]):
    """Aggregate specs -> primitive channels (the AccumulatorCompiler
    decomposition, shared by the operator and mesh lowerings).

    Returns (pre_exprs, agg_channels, finalize_specs): ``pre_exprs`` is the
    pre-projection (identity refs plus any derived channels such as x*x for
    sumsq); a pre-projection is needed iff len(pre_exprs) > len(input_types).
    """
    pre_exprs: List[RowExpression] = [
        InputRef(i, t) for i, t in enumerate(input_types)]
    agg_channels: List[AggChannel] = []
    finalize_specs: List[Tuple[PlanAggregate, List[int]]] = []
    for agg in aggregates:
        comp_channels: List[int] = []
        for prim, ctype in agg.spec.components:
            if agg.channel is None:
                agg_channels.append(AggChannel("count", None, ctype))
                comp_channels.append(len(agg_channels) - 1)
                continue
            in_ref = InputRef(agg.channel, input_types[agg.channel])
            if prim == "sumsq":
                sq = B.call("multiply", in_ref, in_ref)
                pre_exprs.append(_coerce_to(sq, ctype))
                ch = len(pre_exprs) - 1
                agg_channels.append(AggChannel("sum", ch, ctype))
            elif prim in ("sum", "min", "max", "count"):
                arg = in_ref
                if prim == "sum" and arg.type != ctype:
                    pre_exprs.append(_coerce_to(arg, ctype))
                    ch = len(pre_exprs) - 1
                else:
                    ch = agg.channel
                agg_channels.append(AggChannel(prim, ch, ctype))
            elif prim in ("collect", "hll", "kll"):
                agg_channels.append(
                    AggChannel(prim, agg.channel, ctype))
            elif prim == "sumln":
                ln = B.call("ln", _coerce_to(in_ref, T.DOUBLE))
                pre_exprs.append(ln)
                agg_channels.append(
                    AggChannel("sum", len(pre_exprs) - 1, ctype))
            elif prim == "sumhash":
                h = B.call("hash64", in_ref)
                pre_exprs.append(h)
                agg_channels.append(
                    AggChannel("sum", len(pre_exprs) - 1, ctype))
            else:
                raise NotImplementedError(f"agg component {prim}")
            comp_channels.append(len(agg_channels) - 1)
        finalize_specs.append((agg, comp_channels))
    return pre_exprs, agg_channels, finalize_specs


def merge_agg_channels(aggregates: Sequence[PlanAggregate], ngroups: int):
    """FINAL-step channels: re-aggregate each partial component with its
    merge primitive (HashAggregationOperator.Step:61 role)."""
    agg_channels: List[AggChannel] = []
    finalize_specs: List[Tuple[PlanAggregate, List[int]]] = []
    comp_ch = ngroups
    for agg in aggregates:
        comp_channels: List[int] = []
        for prim, ctype in agg.spec.components:
            merge = PhysicalPlanner._FINAL_PRIM[
                prim if prim != "sumsq" else "sum"]
            agg_channels.append(AggChannel(merge, comp_ch, ctype))
            comp_channels.append(len(agg_channels) - 1)
            comp_ch += 1
        finalize_specs.append((agg, comp_channels))
    return agg_channels, finalize_specs


def _finalize(agg: PlanAggregate, comps: List[RowExpression]
              ) -> RowExpression:
    fin = agg.spec.finalize
    if fin == "identity":
        out = comps[0]
        if out.type != agg.spec.result_type:
            out = B.cast(out, agg.spec.result_type)
        return out
    if fin == "avg":
        s, c = comps
        if agg.spec.result_type.name == "double":
            return B.call("divide", _coerce_to(s, T.DOUBLE),
                          B.cast(c, T.DOUBLE))
        return B.call("divide", s, c)
    if fin == "map_agg":
        return B.call("map_from_entries", comps[0])
    if fin in ("min_by", "max_by"):
        return B.call(f"$rows_{fin}", comps[0])
    if fin == "approx_distinct":
        return B.call("$hll_cardinality", comps[0])
    if fin.startswith("approx_percentile:"):
        from presto_tpu.expr import functions as F
        from presto_tpu.expr.ir import Call

        p = float(fin.split(":", 1)[1])
        fn = F.resolve_kll_percentile(agg.spec.result_type, p)
        return Call("$kll_percentile", (comps[0],), fn.result_type, fn)
    if fin in ("corr", "covar_samp", "covar_pop", "regr_slope",
               "regr_intercept"):
        return B.call(f"$rows_{fin}", comps[0])
    if fin in ("learn_classifier", "learn_regressor"):
        return B.call(f"$rows_{fin}", comps[0])
    if fin == "geometric_mean":
        s, n = comps
        return B.call("exp", B.call("divide", s, B.cast(n, T.DOUBLE)))
    if fin in ("stddev_samp", "stddev_pop", "var_samp", "var_pop"):
        s, sq, n = comps
        nd = B.cast(n, T.DOUBLE)
        mean_sq = B.call("divide", B.call("multiply", s, s), nd)
        num = B.call("subtract", sq, mean_sq)
        if fin.endswith("_pop"):
            var = B.call("divide", num, nd)
        else:
            var = B.call("divide", num,
                         B.call("subtract", nd, B.const(1.0, T.DOUBLE)))
        if fin.startswith("stddev"):
            return B.call("sqrt", var)
        return var
    raise NotImplementedError(f"finalize {fin}")


def _extract_constraints(filters, column_names):
    """RowExpression conjuncts -> TupleDomain-lite (col, op, literal)
    triples for Connector.prune_splits.  Only simple comparisons and IN
    over a bare input channel qualify; everything else is ignored (the
    row-level filter still applies)."""
    from presto_tpu.expr.ir import Call, Constant, SpecialForm

    def fold(e):
        """Fold literal-only subtrees (e.g. cast(1:integer) from IN-list
        coercion) to a Constant by evaluating on a zero-channel row."""
        if isinstance(e, Constant) or any(
                isinstance(x, InputRef) for x in _walk(e)):
            return e
        try:
            from presto_tpu.batch import Batch
            from presto_tpu.expr.compile import evaluate

            col = evaluate(e, Batch((), 1))
            if col.valid is not None and not bool(col.valid[0]):
                return Constant(None, e.type)
            v = col.values[0]
            if col.dictionary is not None:
                v = col.dictionary.values[int(v)]
            return Constant(v.item() if hasattr(v, "item") else v, e.type)
        except Exception:
            return e

    conjuncts = []
    stack = list(filters)
    while stack:
        e = stack.pop()
        if isinstance(e, SpecialForm) and e.form == "AND":
            stack.extend(e.args)
        else:
            conjuncts.append(e)
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
            "eq": "eq", "ne": "ne"}
    out = []
    for c in conjuncts:
        if isinstance(c, Call) and c.name in flip and len(c.args) == 2:
            a, b = (fold(x) for x in c.args)
            if isinstance(a, InputRef) and isinstance(b, Constant) \
                    and b.value is not None:
                out.append((column_names[a.index], c.name, b.value))
            elif isinstance(b, InputRef) and isinstance(a, Constant) \
                    and a.value is not None:
                out.append((column_names[b.index], flip[c.name], a.value))
        elif isinstance(c, SpecialForm) and c.form == "IN" and c.args:
            v = c.args[0]
            items = [fold(i) for i in c.args[1:]]
            if isinstance(v, InputRef) and all(
                    isinstance(i, Constant) and i.value is not None
                    for i in items):
                out.append((column_names[v.index], "in",
                            tuple(i.value for i in items)))
    return out


def _walk(e):
    yield e
    for a in getattr(e, "args", ()):
        yield from _walk(a)
