"""Logical plan optimizer.

The reference runs ~40 ordered passes (PlanOptimizers.java:160) with 113
iterative rules; this module implements the subset that changes the game
for the executable query shapes, in the same spirit:

- ``build_join_graph`` + ``extract_joins``: Filter-over-cross-join ->
  equi-join tree with pushed single-relation predicates and residual
  placement (the PredicatePushDown + join-graph part of the reference's
  AddExchanges preparation).  With ``optimizer_use_memo`` on (default)
  the graph feeds the Memo-based ReorderJoins/DetermineJoinDistribution
  exploration in sql/memo.py; this module's greedy orderer (left-deep,
  probe side = largest estimated relation) is the fallback when stats
  are absent or the graph exceeds the enumeration bound.
- ``prune_columns``: unreferenced-output elimination down to the scans
  (PruneUnreferencedOutputs + pushdown-into-scan).
- ``rewrite_distinct_aggregates``: count(DISTINCT x) -> two-level
  aggregation (SingleDistinctAggregationToGroupBy rule analogue).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from presto_tpu import types as T
from presto_tpu.expr import build as B
from presto_tpu.expr.functions import resolve_aggregate
from presto_tpu.expr.ir import (
    Call, Constant, InputRef, RowExpression, SpecialForm, input_channels,
)
from presto_tpu.sql.plan import (
    AggregationNode, EnforceSingleRowNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanAggregate, PlanNode, ProjectNode, SemiJoinNode,
    SortNode, TableScanNode, UnionNode, UnnestNode, ValuesNode, WindowNode,
)


def optimize(plan: OutputNode, metadata=None, config=None) -> OutputNode:
    """Optimizer pipeline.  ``config`` carries the session-steerable
    policies (join_reordering_strategy, ... — the SystemSessionProperties
    reaching PlanOptimizers role); None = engine defaults."""
    from presto_tpu.config import DEFAULT

    from presto_tpu.sql.rules import (
        DEFAULT_RULES, RuleContext, iterative_optimize,
    )

    config = config or DEFAULT
    ctx = RuleContext(metadata, config)
    node = push_filters_down(plan)
    # iterative rule engine (IterativeOptimizer role) runs before join
    # extraction (limits/filters normalize, partial aggs split through
    # unions) and again after (projection-through-join sees the built
    # join tree)
    node = iterative_optimize(node, DEFAULT_RULES, ctx)
    node = _rewrite_bottom_up(node, metadata, config)
    node = iterative_optimize(node, DEFAULT_RULES, ctx)
    node = prune_columns(node)
    return node


# ---------------------------------------------------------------------------
# expression channel remapping
# ---------------------------------------------------------------------------

def remap(expr: RowExpression, mapping: Dict[int, int]) -> RowExpression:
    if isinstance(expr, InputRef):
        return InputRef(mapping[expr.index], expr.type)
    if isinstance(expr, Call):
        return Call(expr.name, tuple(remap(a, mapping) for a in expr.args),
                    expr.type, expr.fn)
    if isinstance(expr, SpecialForm):
        return SpecialForm(expr.form,
                           tuple(remap(a, mapping) for a in expr.args),
                           expr.type)
    return expr


def split_and(expr: RowExpression) -> List[RowExpression]:
    if isinstance(expr, SpecialForm) and expr.form == "AND":
        out: List[RowExpression] = []
        for a in expr.args:
            out.extend(split_and(a))
        return out
    return [expr]


def and_all(exprs: Sequence[RowExpression]) -> RowExpression:
    out = exprs[0]
    for e in exprs[1:]:
        out = B.and_(out, e)
    return out


# ---------------------------------------------------------------------------
# join extraction
# ---------------------------------------------------------------------------

def substitute(expr: RowExpression,
               exprs: Sequence[RowExpression]) -> RowExpression:
    """Replace InputRef(i) with exprs[i] (pushdown through a projection)."""
    if isinstance(expr, InputRef):
        return exprs[expr.index]
    if isinstance(expr, Call):
        return dataclasses.replace(
            expr, args=tuple(substitute(a, exprs) for a in expr.args))
    if isinstance(expr, SpecialForm):
        return dataclasses.replace(
            expr, args=tuple(substitute(a, exprs) for a in expr.args))
    return expr


def _push_filter(node: FilterNode) -> PlanNode:
    """One predicate-pushdown step (PredicatePushDown.java role): move
    eligible conjuncts below Filter/Project/outer-Join/SemiJoin/Union.
    Returns ``node`` unchanged when nothing can move."""
    src = node.source
    conjuncts = split_and(node.predicate)
    if isinstance(src, FilterNode):
        return FilterNode(src.source,
                          and_all(conjuncts + split_and(src.predicate)))
    if isinstance(src, ProjectNode):
        # substitution is safe: projections are pure expressions
        below = [substitute(c, src.expressions) for c in conjuncts]
        return ProjectNode(FilterNode(src.source, and_all(below)),
                           src.expressions, src.columns)
    if isinstance(src, JoinNode) and src.kind in ("left",):
        nleft = len(src.left.columns)
        pushable = [c for c in conjuncts
                    if all(ch < nleft for ch in input_channels(c))]
        rest = [c for c in conjuncts if c not in pushable]
        if pushable:
            new_left = FilterNode(src.left, and_all(pushable))
            new_join = dataclasses.replace(src, left=new_left)
            return (FilterNode(new_join, and_all(rest)) if rest
                    else new_join)
    if isinstance(src, SemiJoinNode):
        nsrc = len(src.source.columns)
        pushable = [c for c in conjuncts
                    if all(ch < nsrc for ch in input_channels(c))]
        rest = [c for c in conjuncts if c not in pushable]
        if pushable:
            new_inner = FilterNode(src.source, and_all(pushable))
            new_semi = dataclasses.replace(src, source=new_inner)
            return (FilterNode(new_semi, and_all(rest)) if rest
                    else new_semi)
    if isinstance(src, UnionNode):
        return UnionNode(tuple(
            FilterNode(inp, node.predicate) for inp in src.inputs),
            src.columns)
    return node


def push_filters_down(node: PlanNode) -> PlanNode:
    """Top-down predicate pushdown to fixpoint: conjuncts only ever move
    downward, so one sweep terminates."""
    while isinstance(node, FilterNode):
        pushed = _push_filter(node)
        if pushed is node:
            break
        node = pushed
    return _replace_sources(node,
                            [push_filters_down(s) for s in node.sources])


def _cross_chain(leaves: List[PlanNode]) -> PlanNode:
    cur = leaves[0]
    for leaf in leaves[1:]:
        cur = JoinNode("cross", cur, leaf, (), (),
                       tuple(cur.columns) + tuple(leaf.columns))
    return cur


def _rewrite_bottom_up(node: PlanNode, metadata, config=None) -> PlanNode:
    # Filter-over-join-chain (and bare chains): flatten BEFORE recursing
    # so WHERE conjuncts and ON keys place together during join
    # reordering (ReorderJoins + PredicatePushDown interplay); recursion
    # descends into the chain's leaves only, so extraction runs once.
    chain = None
    extra: List[RowExpression] = []
    if isinstance(node, FilterNode) and _is_join_chain(node.source):
        chain, extra = node.source, split_and(node.predicate)
    elif _is_join_chain(node) and _chain_size(node) > 2:
        chain = node
    if chain is not None:
        tree, conjs = _flatten_joins(chain)
        leaves = [_rewrite_bottom_up(l, metadata, config)
                  for l in _cross_leaves(tree)]
        tree = _cross_chain(leaves)
        conjs = conjs + extra
        if conjs:
            fnode = FilterNode(tree, and_all(conjs))
            if (config is not None and config.optimizer_use_memo
                    and config.join_reordering_strategy != "none"):
                from presto_tpu.sql.memo import try_memo_extract_joins

                out = try_memo_extract_joins(fnode, metadata, config)
                if out is not None:   # None: stats absent / graph too big
                    return out
            return extract_joins(fnode, metadata, config)
        return tree

    node = _replace_sources(
        node, [_rewrite_bottom_up(s, metadata, config) for s in node.sources])
    if isinstance(node, AggregationNode) and any(
            a.distinct for a in node.aggregates):
        return rewrite_distinct_aggregates(node)
    return node


def _replace_sources(node: PlanNode,
                     sources: List[PlanNode]) -> PlanNode:
    if not sources:
        return node
    fields: Dict[str, object] = {}
    names = [f.name for f in dataclasses.fields(node)]
    if "source" in names:
        fields["source"] = sources[0]
    if "left" in names:
        fields["left"] = sources[0]
        fields["right"] = sources[1]
    if "filtering" in names:
        fields["source"] = sources[0]
        fields["filtering"] = sources[1]
    if "inputs" in names:
        fields["inputs"] = tuple(sources)
    return dataclasses.replace(node, **fields)


def _is_cross_tree(node: PlanNode) -> bool:
    return (isinstance(node, JoinNode) and node.kind == "cross"
            and not node.left_keys)


def _is_join_chain(node: PlanNode) -> bool:
    """A tree of cross/inner joins (flattenable for reorder+pushdown)."""
    return isinstance(node, JoinNode) and node.kind in ("cross", "inner")


def _chain_size(node: PlanNode) -> int:
    if _is_join_chain(node):
        return _chain_size(node.left) + _chain_size(node.right)  # type: ignore[attr-defined]
    return 1


def _flatten_joins(node: PlanNode) -> Tuple[PlanNode, List[RowExpression]]:
    """Inner/cross join tree -> (pure cross tree, conjuncts) in the tree's
    own output channel space; join keys become equality conjuncts and
    residuals are re-split.  Channel layout is preserved because inner and
    cross joins both concatenate left+right columns."""
    if not _is_join_chain(node):
        return node, []
    assert isinstance(node, JoinNode)
    lt, lc = _flatten_joins(node.left)
    rt, rc = _flatten_joins(node.right)
    nleft = len(node.left.columns)
    conjs = list(lc)
    for c in rc:
        conjs.append(remap(c, {ch: ch + nleft
                               for ch in input_channels(c)}))
    for lk, rk in zip(node.left_keys, node.right_keys):
        conjs.append(B.comparison(
            "=", InputRef(lk, node.left.columns[lk][1]),
            InputRef(nleft + rk, node.right.columns[rk][1])))
    if node.residual is not None:
        conjs.extend(split_and(node.residual))
    tree = JoinNode("cross", lt, rt, (), (), node.columns)
    return tree, conjs


def _cross_leaves(node: PlanNode) -> List[PlanNode]:
    if _is_cross_tree(node):
        return _cross_leaves(node.left) + _cross_leaves(node.right)  # type: ignore[attr-defined]
    return [node]


def _estimate_rows(node: PlanNode, metadata,
                   calculator=None) -> float:
    """Stats-driven row estimate (the StatsCalculator entry used by join
    ordering and the fragmenter's distribution choice); heuristic
    fallbacks apply only where the derivation reports unknown."""
    from presto_tpu.sql.stats import StatsCalculator

    sc = calculator or StatsCalculator(metadata)
    rc = sc.stats(node).row_count
    if rc is not None:
        return rc
    if isinstance(node, TableScanNode):
        return 1e6
    if isinstance(node, (FilterNode, ProjectNode, LimitNode, SortNode)):
        return _estimate_rows(node.sources[0], metadata, sc) * (
            0.3 if isinstance(node, FilterNode) else 1.0)
    if isinstance(node, AggregationNode):
        return _estimate_rows(node.sources[0], metadata, sc) * 0.1
    if isinstance(node, JoinNode):
        return max(_estimate_rows(node.left, metadata, sc),
                   _estimate_rows(node.right, metadata, sc))
    if isinstance(node, SemiJoinNode):
        return _estimate_rows(node.sources[0], metadata, sc)
    if isinstance(node, EnforceSingleRowNode):
        return 1.0
    return 1e4


def factor_or_conjuncts(expr: RowExpression) -> List[RowExpression]:
    """OR(a AND x, a AND y) -> [a, OR(x, y)] (ExtractCommonPredicates
    rewriter analogue) — lets each OR branch's shared join equalities
    become join keys (TPC-H Q19's p_partkey = l_partkey)."""
    if not (isinstance(expr, SpecialForm) and expr.form == "OR"):
        return [expr]
    branches = []
    stack = [expr]
    while stack:
        e = stack.pop()
        if isinstance(e, SpecialForm) and e.form == "OR":
            stack.extend(e.args)
        else:
            branches.append(split_and(e))
    common = [c for c in branches[0]
              if all(any(c == d for d in b) for b in branches[1:])]
    if not common:
        return [expr]
    out = list(common)
    rests = []
    for b in branches:
        rest = [c for c in b if not any(c == d for d in common)]
        if not rest:
            return out  # one branch is fully covered: OR part is TRUE
        rests.append(and_all(rest))
    ored = rests[0]
    for r in rests[1:]:
        ored = B.or_(ored, r)
    out.append(ored)
    return out


@dataclasses.dataclass
class JoinGraph:
    """The join graph shared by the greedy orderer and the memo-based
    ``ReorderJoins`` exploration (sql/memo.py): filtered leaves, equality
    edges (direct first, then transitively-derived), and the residual
    conjuncts that could not push or become keys.  Channels in
    ``residual`` are in the ORIGINAL concatenated leaf space."""

    nodes: List[PlanNode]                      # leaves w/ pushed filters
    offsets: List[int]                         # original channel offsets
    edges: List[Tuple[int, int, int, int]]     # (leaf_a, ch_a, leaf_b, ch_b)
    derived_from: int                          # edges[:derived_from] direct
    residual: List[RowExpression]
    columns: Tuple                             # original concat columns

    def leaf_of(self, ch: int) -> int:
        for i in range(len(self.nodes) - 1, -1, -1):
            if ch >= self.offsets[i]:
                return i
        raise AssertionError


def build_join_graph(filter_node: FilterNode) -> JoinGraph:
    """Filter(cross-join tree) -> JoinGraph: push single-leaf conjuncts
    onto their leaves, classify two-leaf equalities as edges, run the
    transitive equality inference (EqualityInference.java role), and
    keep the rest as residual conjuncts."""
    leaves = _cross_leaves(filter_node.source)
    offsets = []
    off = 0
    for leaf in leaves:
        offsets.append(off)
        off += len(leaf.columns)

    def leaf_of(ch: int) -> int:
        for i in range(len(leaves) - 1, -1, -1):
            if ch >= offsets[i]:
                return i
        raise AssertionError

    conjuncts = []
    for c in split_and(filter_node.predicate):
        conjuncts.extend(factor_or_conjuncts(c))
    pushed: List[List[RowExpression]] = [[] for _ in leaves]
    edges: List[Tuple[int, int, int, int]] = []  # (la, cha, lb, chb)
    residual: List[RowExpression] = []
    for c in conjuncts:
        chans = input_channels(c)
        ls = {leaf_of(ch) for ch in chans}
        if len(ls) == 1:
            li = ls.pop()
            pushed[li].append(
                remap(c, {ch: ch - offsets[li] for ch in chans}))
        elif (len(ls) == 2 and isinstance(c, Call) and c.name == "eq"
                and len(c.args) == 2
                and all(isinstance(a, InputRef) for a in c.args)):
            a, b = c.args  # type: ignore[misc]
            la, lb = leaf_of(a.index), leaf_of(b.index)
            edges.append((la, a.index - offsets[la],
                          lb, b.index - offsets[lb]))
        else:
            residual.append(c)

    # Transitive equality inference (EqualityInference.java role):
    # equivalence classes over the equality edges (a) replicate
    # single-column constant predicates to every equivalent column's
    # leaf (o_orderkey < K infers l_orderkey < K through
    # l_orderkey = o_orderkey), and (b) derive join edges between leaf
    # pairs connected only transitively, giving the reorderer equi-join
    # options where it would otherwise cross-join.  Derived edges are
    # implied by the direct ones (every class is spanned by enforced
    # direct edges), so they never become post-join filters.
    parent: Dict[Tuple[int, int], Tuple[int, int]] = {}

    def find(x):
        r = x
        while parent.get(r, r) != r:
            r = parent[r]
        while parent.get(x, x) != x:
            parent[x], x = r, parent[x]
        return r

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    for la, ca, lb, cb in edges:
        union((la, ca), (lb, cb))
    classes: Dict[Tuple[int, int], List[Tuple[int, int]]] = {}
    for la, ca, lb, cb in edges:
        for m in ((la, ca), (lb, cb)):
            classes.setdefault(find(m), [])
            if m not in classes[find(m)]:
                classes[find(m)].append(m)

    def _constant_pred_channel(p: RowExpression) -> Optional[int]:
        """The single input channel of a comparison-vs-constant."""
        if not (isinstance(p, Call) and len(p.args) == 2
                and p.name in ("eq", "ne", "lt", "le", "gt", "ge")):
            return None
        chans = input_channels(p)
        if len(chans) != 1:
            return None
        if not any(isinstance(a, Constant) for a in p.args):
            return None
        return next(iter(chans))

    for li in range(len(leaves)):
        for p in list(pushed[li]):
            ch = _constant_pred_channel(p)
            if ch is None:
                continue
            for lj, cj in classes.get(find((li, ch)), ()):
                if lj == li:
                    continue
                repl = remap(p, {ch: cj})
                if not any(repl == q for q in pushed[lj]):
                    pushed[lj].append(repl)
    direct_pairs = {frozenset((la, lb)) for la, _, lb, _ in edges}
    derived_from = len(edges)
    for members in classes.values():
        for i in range(len(members)):
            for j in range(i + 1, len(members)):
                (la, ca), (lb, cb) = members[i], members[j]
                if la == lb or frozenset((la, lb)) in direct_pairs:
                    continue
                edges.append((la, ca, lb, cb))
                direct_pairs.add(frozenset((la, lb)))

    nodes: List[PlanNode] = []
    for leaf, preds in zip(leaves, pushed):
        nodes.append(FilterNode(leaf, and_all(preds)) if preds else leaf)

    return JoinGraph(nodes, offsets, edges, derived_from, residual,
                     tuple(col for leaf in leaves for col in leaf.columns))


def extract_joins(filter_node: FilterNode, metadata, config=None) -> PlanNode:
    """Filter(cross-join tree) -> pushed filters + left-deep equi joins."""
    graph = build_join_graph(filter_node)
    nodes = graph.nodes
    offsets = graph.offsets
    edges = graph.edges
    derived_from = graph.derived_from
    residual = graph.residual
    leaf_of = graph.leaf_of

    # greedy left-deep order: start at the largest relation (probe side);
    # at each step join the connected relation whose join yields the
    # SMALLEST estimated intermediate (the ReorderJoins cost objective,
    # evaluated through the stats derivation instead of leaf sizes alone)
    from presto_tpu.sql.stats import StatsCalculator

    sc = StatsCalculator(metadata)
    sizes = [_estimate_rows(n, metadata, sc) for n in nodes]
    remaining = set(range(len(nodes)))
    # join_reordering_strategy=none keeps the syntactic order
    syntactic = (config is not None
                 and config.join_reordering_strategy == "none")
    start = 0 if syntactic else max(remaining, key=lambda i: sizes[i])
    joined = [start]
    remaining.discard(start)
    current = nodes[start]
    # channel map: (leaf, local_ch) -> current output channel
    chan_map: Dict[Tuple[int, int], int] = {
        (start, i): i for i in range(len(nodes[start].columns))}
    used_edges = [False] * len(edges)
    pending_residual = list(residual)

    def candidate_keys(nxt: int
                       ) -> Tuple[List[int], List[int], List[int]]:
        """Join keys (and their edge indices) connecting the joined
        prefix to ``nxt`` — the ONE source of truth for both costing a
        candidate and building the chosen join."""
        lks: List[int] = []
        rks: List[int] = []
        eis: List[int] = []
        for i, (la, ca, lb, cb) in enumerate(edges):
            if used_edges[i]:
                continue
            if la in joined and lb == nxt:
                lks.append(chan_map[(la, ca)])
                rks.append(cb)
                eis.append(i)
            elif lb in joined and la == nxt:
                lks.append(chan_map[(lb, cb)])
                rks.append(ca)
                eis.append(i)
        return lks, rks, eis

    def connected() -> Optional[int]:
        candidates = set()
        for i, (la, _, lb, _) in enumerate(edges):
            if used_edges[i]:
                continue
            if la in joined and lb in remaining:
                candidates.add(lb)
            if lb in joined and la in remaining:
                candidates.add(la)
        if candidates:
            if syntactic:
                return min(candidates)

            def join_cost(i: int) -> Tuple[float, float]:
                lks, rks, _ = candidate_keys(i)
                cols = current.columns + nodes[i].columns
                kind = "inner" if lks else "cross"
                probe = JoinNode(kind, current, nodes[i],
                                 tuple(lks), tuple(rks), cols)
                return (_estimate_rows(probe, metadata, sc), sizes[i])

            return min(candidates, key=join_cost)
        return next(iter(remaining)) if remaining else None

    while remaining:
        nxt = connected()
        if nxt is None:
            break
        nxt_node = nodes[nxt]
        left_keys, right_keys, edge_idx = candidate_keys(nxt)
        for i in edge_idx:
            used_edges[i] = True
        base = len(current.columns)
        cols = current.columns + nxt_node.columns
        if left_keys:
            current = JoinNode("inner", current, nxt_node,
                               tuple(left_keys), tuple(right_keys), cols)
        else:
            current = JoinNode("cross", current, nxt_node, (), (), cols)
        for j in range(len(nxt_node.columns)):
            chan_map[(nxt, j)] = base + j
        joined.append(nxt)
        remaining.discard(nxt)

        # equality edges whose both leaves are now joined but were not
        # usable as keys become filters immediately (current-space refs)
        extra_now: List[RowExpression] = []
        for i, (la, ca, lb, cb) in enumerate(edges):
            if not used_edges[i] and la in joined and lb in joined:
                used_edges[i] = True
                if i >= derived_from:
                    # transitively-derived edge: implied by the direct
                    # edges (all enforced as keys or filters) — adding a
                    # filter would just re-check a=c after a=b and b=c
                    continue
                extra_now.append(
                    B.comparison("=",
                                 _ref_at(current, chan_map[(la, ca)]),
                                 _ref_at(current, chan_map[(lb, cb)])))
        if extra_now:
            current = FilterNode(current, and_all(extra_now))
        ready = []
        rest = []
        for c in pending_residual:
            chans = input_channels(c)
            if all(leaf_of(ch) in joined for ch in chans):
                ready.append(remap(c, {
                    ch: chan_map[(leaf_of(ch), ch - offsets[leaf_of(ch)])]
                    for ch in chans}))
            else:
                rest.append(c)
        pending_residual = rest
        if ready:
            current = FilterNode(current, and_all(ready))

    return restore_leaf_order(graph, current, chan_map)


def restore_leaf_order(graph: JoinGraph, current: PlanNode,
                       chan_map: Dict[Tuple[int, int], int]) -> PlanNode:
    """Project the ordered join tree back to the original concatenated
    leaf channel order for the parent (shared greedy/memo epilogue)."""
    out_exprs = []
    for li, leaf in enumerate(graph.nodes):
        for j in range(len(leaf.columns)):
            ch = chan_map[(li, j)]
            out_exprs.append(InputRef(ch, current.columns[ch][1]))
    return ProjectNode(current, tuple(out_exprs), graph.columns)


def _ref_at(node: PlanNode, ch: int) -> InputRef:
    # build an InputRef in the *pre-remap* leaf space is incorrect here;
    # these equality folds are already in current-channel space, so remap
    # in the caller is an identity for them — construct directly.
    return InputRef(ch, node.columns[ch][1])


# ---------------------------------------------------------------------------
# distinct aggregate rewrite
# ---------------------------------------------------------------------------

def rewrite_distinct_aggregates(node: AggregationNode) -> PlanNode:
    """Aggregate(keys, [agg(distinct x)]) ->
    Aggregate(keys, [agg(x)]) over Aggregate(keys + x, []).

    Several distinct channels and/or mixed DISTINCT + plain aggregates
    split into one aggregation per distinct channel (plus one for plains)
    over the same source, joined back on the group keys (the role the
    reference's MarkDistinct/OptimizeMixedDistinctAggregations rewrites
    play; a join on NULL group keys drops them, an accepted divergence
    noted here)."""
    d_channels = sorted({a.channel for a in node.aggregates if a.distinct})
    if len(d_channels) == 1 and all(a.distinct for a in node.aggregates):
        return _rewrite_one_distinct_channel(node)
    return _rewrite_split_distinct(node, d_channels)


def _rewrite_one_distinct_channel(node: AggregationNode) -> PlanNode:
    """All aggregates DISTINCT over the same single input channel."""
    in_channels = sorted({a.channel for a in node.aggregates
                          if a.channel is not None})
    inner_keys = tuple(node.group_channels) + tuple(in_channels)
    src = node.source
    inner_cols = tuple(src.columns[c] for c in inner_keys)
    inner = AggregationNode(src, inner_keys, (), inner_cols)
    ch_pos = {c: i for i, c in enumerate(inner_keys)}
    aggs = []
    for a in node.aggregates:
        spec = a.spec
        if spec.name in ("count", "count_star"):
            # count(distinct x): count non-null x per group
            arg_t = inner_cols[ch_pos[a.channel]][1]
            spec = resolve_aggregate("count", arg_t)
        aggs.append(PlanAggregate(spec,
                                  ch_pos.get(a.channel), False,
                                  a.output_name))
    return AggregationNode(inner,
                           tuple(range(len(node.group_channels))),
                           tuple(aggs), node.columns)


def _rewrite_split_distinct(node: AggregationNode,
                            d_channels: List[int]) -> PlanNode:
    """One aggregation branch per distinct channel + one for plain
    aggregates, all over the same source, joined on the group keys
    (cross join of single rows in the global case)."""
    ngroups = len(node.group_channels)
    key_cols = tuple(node.columns[:ngroups])

    parts: List[List[int]] = []          # aggregate indices per branch
    for ch in d_channels:
        parts.append([i for i, a in enumerate(node.aggregates)
                      if a.distinct and a.channel == ch])
    plains = [i for i, a in enumerate(node.aggregates) if not a.distinct]
    if plains:
        parts.append(plains)

    def agg_node(indices: List[int]) -> PlanNode:
        aggs = tuple(node.aggregates[i] for i in indices)
        cols = key_cols + tuple(node.columns[ngroups + i] for i in indices)
        branch = AggregationNode(node.source, node.group_channels, aggs,
                                 cols)
        if any(a.distinct for a in aggs):
            return _rewrite_one_distinct_channel(branch)
        return branch

    branches = [agg_node(p) for p in parts]
    joined = branches[0]
    # position of each original aggregate in the joined output
    agg_pos: Dict[int, int] = {i: ngroups + k
                               for k, i in enumerate(parts[0])}
    for branch, part in zip(branches[1:], parts[1:]):
        base = len(joined.columns)
        out_cols = tuple(joined.columns) + tuple(branch.columns)
        if ngroups:
            keys = tuple(range(ngroups))
            joined = JoinNode("inner", joined, branch, keys, keys,
                              out_cols)
        else:
            joined = JoinNode("cross", joined, branch, (), (), out_cols)
        for k, i in enumerate(part):
            agg_pos[i] = base + ngroups + k
    # restore the original column order: keys, then aggregates in order
    exprs: List[RowExpression] = [
        InputRef(i, t) for i, (_, t) in enumerate(key_cols)]
    for i in range(len(node.aggregates)):
        ch = agg_pos[i]
        exprs.append(InputRef(ch, joined.columns[ch][1]))
    return ProjectNode(joined, tuple(exprs), node.columns)


# ---------------------------------------------------------------------------
# column pruning
# ---------------------------------------------------------------------------

def prune_columns(plan: OutputNode) -> OutputNode:
    src, mapping = _prune(plan.source,
                          sorted(range(len(plan.source.columns))))
    return dataclasses.replace(plan, source=src)


def _prune(node: PlanNode,
           needed: List[int]) -> Tuple[PlanNode, Dict[int, int]]:
    """Returns (pruned node, old-channel -> new-channel mapping covering at
    least ``needed``)."""
    if not needed:
        needed = [0]  # count(*)-style shapes still need row counts
    if isinstance(node, TableScanNode):
        names = [node.column_names[i] for i in needed]
        cols = tuple(node.columns[i] for i in needed)
        return (dataclasses.replace(node, column_names=tuple(names),
                                    columns=cols),
                {ch: i for i, ch in enumerate(needed)})
    if isinstance(node, ValuesNode):
        cols = tuple(node.columns[i] for i in needed)
        rows = tuple(tuple(r[i] for i in needed) for r in node.rows)
        return (ValuesNode(cols, rows),
                {ch: i for i, ch in enumerate(needed)})
    if isinstance(node, FilterNode):
        child_needed = sorted(set(needed)
                              | set(input_channels(node.predicate)))
        src, m = _prune(node.source, child_needed)
        return (FilterNode(src, remap(node.predicate, m)),
                {ch: m[ch] for ch in needed})
    if isinstance(node, ProjectNode):
        child_needed = sorted({ch for i in needed
                               for ch in input_channels(
                                   node.expressions[i])})
        src, m = _prune(node.source, child_needed)
        exprs = tuple(remap(node.expressions[i], m) for i in needed)
        cols = tuple(node.columns[i] for i in needed)
        return (ProjectNode(src, exprs, cols),
                {ch: i for i, ch in enumerate(needed)})
    if isinstance(node, AggregationNode):
        ngroups = len(node.group_channels)
        if node.step != "single":
            # partial/final pairs speak the positional component-column
            # contract (keys, then each spec's components in order):
            # pruning through would desync the layouts — keep the full
            # source schema
            src, m = _prune(node.source,
                            sorted(range(len(node.source.columns))))
            new_node = _replace_sources(node, [src])
            return new_node, {ch: ch for ch in needed}
        # the empty-needed [0] fallback can point past a zero-column
        # aggregation (grouping-sets grand-total branch); clamp to
        # channels the node actually has
        agg_needed = [i - ngroups for i in needed
                      if ngroups <= i < ngroups + len(node.aggregates)]
        keep_aggs = [node.aggregates[i] for i in agg_needed]
        child_needed = sorted(set(node.group_channels)
                              | {a.channel for a in keep_aggs
                                 if a.channel is not None})
        src, m = _prune(node.source, child_needed)
        aggs = tuple(dataclasses.replace(
            a, channel=None if a.channel is None else m[a.channel])
            for a in keep_aggs)
        out_cols = (tuple(node.columns[:ngroups])
                    + tuple(node.columns[ngroups + i] for i in agg_needed))
        new_node = AggregationNode(
            src, tuple(m[c] for c in node.group_channels), aggs, out_cols,
            node.step)
        mapping = {c: i for i, c in enumerate(range(ngroups))}
        for newpos, i in enumerate(agg_needed):
            mapping[ngroups + i] = ngroups + newpos
        return new_node, {ch: mapping[ch] for ch in
                          list(range(ngroups)) + [n + ngroups
                                                  for n in agg_needed]}
    if isinstance(node, JoinNode):
        nleft = len(node.left.columns)
        res_chans = (input_channels(node.residual)
                     if node.residual is not None else ())
        left_needed = sorted({ch for ch in set(needed) | set(res_chans)
                              if ch < nleft} | set(node.left_keys))
        right_needed = sorted({ch - nleft
                               for ch in set(needed) | set(res_chans)
                               if ch >= nleft} | set(node.right_keys))
        lsrc, lm = _prune(node.left, left_needed)
        rsrc, rm = _prune(node.right, right_needed)
        nleft_new = len(lsrc.columns)
        mapping = {}
        for ch in left_needed:
            mapping[ch] = lm[ch]
        for ch in right_needed:
            mapping[ch + nleft] = rm[ch] + nleft_new
        # children may keep extra channels (their own join keys), so the
        # pruned schema comes from their ACTUAL outputs
        cols = tuple(lsrc.columns) + tuple(rsrc.columns)
        residual = (remap(node.residual, mapping)
                    if node.residual is not None else None)
        new_node = JoinNode(node.kind, lsrc, rsrc,
                            tuple(lm[c] for c in node.left_keys),
                            tuple(rm[c] for c in node.right_keys),
                            cols, residual, node.distribution)
        return new_node, {ch: mapping[ch] for ch in needed}
    if isinstance(node, SemiJoinNode):
        nsrc = len(node.source.columns)
        res_chans = (input_channels(node.residual)
                     if node.residual is not None else ())
        src_needed = sorted({ch for ch in set(needed) | set(res_chans)
                             if ch < nsrc} | set(node.source_keys))
        filt_needed = sorted({ch - nsrc for ch in res_chans
                              if ch >= nsrc} | set(node.filtering_keys))
        ssrc, sm = _prune(node.source, src_needed)
        fsrc, fm = _prune(node.filtering, filt_needed)
        mapping = {}
        for ch in src_needed:
            mapping[ch] = sm[ch]
        for ch in filt_needed:
            mapping[ch + nsrc] = fm[ch] + len(ssrc.columns)
        residual = (remap(node.residual, mapping)
                    if node.residual is not None else None)
        new_node = SemiJoinNode(ssrc, fsrc,
                                tuple(sm[c] for c in node.source_keys),
                                tuple(fm[c] for c in node.filtering_keys),
                                node.negated, residual, node.null_aware)
        return new_node, {ch: sm[ch] for ch in needed}
    if isinstance(node, SortNode):
        child_needed = sorted(set(needed)
                              | {c for c, _, _ in node.sort_keys})
        src, m = _prune(node.source, child_needed)
        keys = tuple((m[c], asc, nf) for c, asc, nf in node.sort_keys)
        return SortNode(src, keys), {ch: m[ch] for ch in needed}
    if isinstance(node, LimitNode):
        src, m = _prune(node.source, needed)
        return LimitNode(src, node.count), m
    if isinstance(node, EnforceSingleRowNode):
        # must keep all columns (the NULL-row synthesis needs the schema)
        src, m = _prune(node.source,
                        sorted(range(len(node.source.columns))))
        return EnforceSingleRowNode(src), m
    if isinstance(node, OutputNode):
        src, m = _prune(node.source, needed)
        return dataclasses.replace(node, source=src), m
    if isinstance(node, WindowNode):
        # keep the full source schema (window output is source-prefix +
        # function channels); prune only unused function channels
        n_src = len(node.source.columns)
        src, m = _prune(node.source, sorted(range(n_src)))
        keep = [i for i in range(len(node.functions))
                if (n_src + i) in needed]
        funcs = tuple(node.functions[i] for i in keep)
        cols = (tuple(src.columns)
                + tuple(node.columns[n_src + i] for i in keep))
        new_node = WindowNode(src, node.partition_channels,
                              node.order_keys, funcs, cols)
        mapping = {ch: ch for ch in range(n_src)}
        for newpos, i in enumerate(keep):
            mapping[n_src + i] = n_src + newpos
        return new_node, {ch: mapping[ch] for ch in needed}
    if isinstance(node, UnnestNode):
        # no pruning through unnest: its output layout is positional
        src, m = _prune(node.source,
                        sorted(range(len(node.source.columns))))
        new_node = dataclasses.replace(
            node, source=src,
            replicate_channels=tuple(m[c] for c in node.replicate_channels),
            unnest_channels=tuple(m[c] for c in node.unnest_channels))
        return new_node, {ch: ch for ch in needed}
    if isinstance(node, UnionNode):
        pruned = []
        for inp in node.inputs:
            src, m = _prune(inp, list(needed))
            # normalize each branch to exactly `needed` order
            if [m[ch] for ch in needed] != list(range(len(needed))):
                exprs = tuple(InputRef(m[ch], node.columns[ch][1])
                              for ch in needed)
                cols = tuple(node.columns[ch] for ch in needed)
                src = ProjectNode(src, exprs, cols)
            pruned.append(src)
        cols = tuple(node.columns[ch] for ch in needed)
        return (UnionNode(tuple(pruned), cols),
                {ch: i for i, ch in enumerate(needed)})
    raise NotImplementedError(f"prune: {type(node).__name__}")
