"""SQL lexer.

Token stream for the recursive-descent parser (the role ANTLR's generated
lexer plays for SqlBase.g4 in the reference).  Keywords are recognized
case-insensitively; identifiers fold to lowercase, double-quoted included
(the reference's legacy canonicalization — `"YEAR"` resolves as "year").
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List


class SqlSyntaxError(ValueError):
    def __init__(self, message: str, line: int, col: int):
        super().__init__(f"line {line}:{col}: {message}")
        self.line = line
        self.col = col


@dataclasses.dataclass(frozen=True)
class Token:
    kind: str      # IDENT QIDENT NUMBER STRING OP KEYWORD EOF
    text: str      # normalized: keywords/idents lowercased
    line: int
    col: int


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "exists", "between", "like", "escape",
    "is", "null", "true", "false", "case", "when", "then", "else", "end",
    "cast", "extract", "interval", "date", "time", "timestamp", "join",
    "inner", "left", "right", "full", "outer", "cross", "on", "asc",
    "desc", "nulls", "first", "last", "distinct", "all", "union", "except",
    "intersect", "with", "explain", "analyze", "show", "tables", "columns",
    "substring", "for", "coalesce", "nullif", "year", "month", "day",
    "hour", "minute", "second", "over", "partition", "rows", "range",
    "unbounded", "preceding", "following", "current", "row", "create",
    "table", "insert", "into", "drop", "values", "set", "reset", "session",
    "grouping", "sets", "rollup", "cube", "array", "unnest", "ordinality",
    "call",
}

_TWO_CHAR = ("<=", ">=", "<>", "!=", "||", "->")
_ONE_CHAR = "+-*/%(),.;<>=[]?"


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i, line, col = 0, 1, 1
    n = len(sql)

    def advance(k: int):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and sql[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            advance(1)
            continue
        if c == "-" and sql[i:i + 2] == "--":
            while i < n and sql[i] != "\n":
                advance(1)
            continue
        if c == "/" and sql[i:i + 2] == "/*":
            start_line, start_col = line, col
            advance(2)
            while i < n and sql[i:i + 2] != "*/":
                advance(1)
            if i >= n:
                raise SqlSyntaxError("unterminated comment", start_line,
                                     start_col)
            advance(2)
            continue
        if c == "'":
            start_line, start_col = line, col
            advance(1)
            buf = []
            while True:
                if i >= n:
                    raise SqlSyntaxError("unterminated string", start_line,
                                         start_col)
                if sql[i] == "'":
                    if sql[i + 1:i + 2] == "'":  # '' escape
                        buf.append("'")
                        advance(2)
                        continue
                    advance(1)
                    break
                buf.append(sql[i])
                advance(1)
            out.append(Token("STRING", "".join(buf), start_line, start_col))
            continue
        if c == '"':
            start_line, start_col = line, col
            advance(1)
            buf = []
            while i < n and sql[i] != '"':
                buf.append(sql[i])
                advance(1)
            if i >= n:
                raise SqlSyntaxError("unterminated quoted identifier",
                                     start_line, start_col)
            advance(1)
            # the reference canonicalizes ALL identifiers to lowercase,
            # quoted included (legacy Presto folding: `"YEAR"` == "year";
            # TPC-DS q66/q74 alias "YEAR" then reference "year")
            out.append(Token("QIDENT", "".join(buf).lower(),
                             start_line, start_col))
            continue
        if c.isdigit() or (c == "." and sql[i + 1:i + 2].isdigit()):
            start_line, start_col = line, col
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                        sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                    seen_exp = True
                    j += 2 if sql[j + 1] in "+-" else 1
                else:
                    break
            text = sql[i:j]
            advance(j - i)
            out.append(Token("NUMBER", text, start_line, start_col))
            continue
        if c.isalpha() or c == "_":
            start_line, start_col = line, col
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j].lower()
            advance(j - i)
            kind = "KEYWORD" if word in KEYWORDS else "IDENT"
            out.append(Token(kind, word, start_line, start_col))
            continue
        two = sql[i:i + 2]
        if two in _TWO_CHAR:
            out.append(Token("OP", two, line, col))
            advance(2)
            continue
        if c in _ONE_CHAR:
            out.append(Token("OP", c, line, col))
            advance(1)
            continue
        raise SqlSyntaxError(f"unexpected character {c!r}", line, col)
    out.append(Token("EOF", "", line, col))
    return out
