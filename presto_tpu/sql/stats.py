"""Plan statistics derivation: the cost-based-optimizer substrate.

The reference derives per-PlanNode estimates through 40+ ``*StatsRule``
classes (presto-main/src/main/java/io/prestosql/cost/ —
``FilterStatsCalculator.java``, ``JoinStatsRule.java``,
``AggregationStatsRule.java``, ``StatsNormalizer``), which feed
``DetermineJoinDistributionType.java:50`` and ``ReorderJoins``.  This
module is that substrate: one bottom-up derivation over the channel-based
plan IR, carrying per-channel (ndv, nulls_fraction, low, high) beside the
row count.

The vocabulary mirrors the reference's:
- unknown stays unknown (``None``), never silently defaults — consumers
  choose their own fallbacks, like PlanNodeStatsEstimate.isOutputRowCountUnknown;
- filters use range interpolation for comparisons and 1/ndv for equality,
  with the reference's UNKNOWN_FILTER_COEFFICIENT (0.9) for opaque
  predicates (FilterStatsCalculator.java);
- equi-joins use |L|*|R| / max(ndv_l, ndv_r) per clause with independence
  across clauses (JoinStatsRule.java);
- aggregations cap the group count by the product of key NDVs
  (AggregationStatsRule.java).
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import Dict, Optional, Tuple

from presto_tpu.expr.ir import (
    Call, Constant, InputRef, RowExpression, SpecialForm, input_channels,
)
from presto_tpu.sql.plan import (
    AggregationNode, EnforceSingleRowNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanNode, ProjectNode, RemoteMergeNode, RemoteSourceNode,
    SemiJoinNode, SortNode, TableScanNode, UnionNode, UnnestNode,
    ValuesNode, WindowNode,
)

# the reference's FilterStatsCalculator.UNKNOWN_FILTER_COEFFICIENT
UNKNOWN_FILTER_COEFFICIENT = 0.9


@dataclasses.dataclass
class ColumnStats:
    """Per-channel statistics (cost/SymbolStatsEstimate role)."""

    ndv: Optional[float] = None
    nulls_fraction: float = 0.0
    low: Optional[float] = None    # numeric-comparable domain value
    high: Optional[float] = None


@dataclasses.dataclass
class PlanStats:
    """Per-node estimate (PlanNodeStatsEstimate role)."""

    row_count: Optional[float]
    columns: Dict[int, ColumnStats] = dataclasses.field(default_factory=dict)

    def col(self, ch: int) -> ColumnStats:
        return self.columns.get(ch, ColumnStats())


def _as_number(value) -> Optional[float]:
    """Literal -> comparable float (dates become epoch days)."""
    if value is None:
        return None
    if isinstance(value, bool):
        return float(value)
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, datetime.date):
        return float(value.toordinal() - datetime.date(1970, 1, 1).toordinal())
    return None


class StatsCalculator:
    """Memoized bottom-up derivation (StatsCalculator/CachingStatsProvider)."""

    def __init__(self, metadata=None):
        self.metadata = metadata
        # memo holds (node, stats): keeping the node referenced prevents
        # CPython from recycling its id() for a different (e.g. throwaway
        # join-ordering probe) node, which would alias cache entries
        self._cache: Dict[int, Tuple[PlanNode, PlanStats]] = {}

    def stats(self, node: PlanNode) -> PlanStats:
        hit = self._cache.get(id(node))
        if hit is not None and hit[0] is node:
            return hit[1]
        derived = self._derive(node)
        self._cache[id(node)] = (node, derived)
        return derived

    def row_count(self, node: PlanNode,
                  default: float = float("inf")) -> float:
        rc = self.stats(node).row_count
        return default if rc is None else rc

    # ------------------------------------------------------------------
    def _derive(self, node: PlanNode) -> PlanStats:
        if isinstance(node, TableScanNode):
            return self._scan_stats(node)
        if isinstance(node, ValuesNode):
            return PlanStats(float(len(node.rows)))
        if isinstance(node, FilterNode):
            return self._filter_stats(node)
        if isinstance(node, ProjectNode):
            return self._project_stats(node)
        if isinstance(node, AggregationNode):
            return self._agg_stats(node)
        if isinstance(node, JoinNode):
            return self._join_stats(node)
        if isinstance(node, SemiJoinNode):
            return self._semijoin_stats(node)
        if isinstance(node, (SortNode, WindowNode)):
            return self.stats(node.sources[0])
        if isinstance(node, LimitNode):
            src = self.stats(node.source)
            rc = (float(node.count) if src.row_count is None
                  else min(src.row_count, float(node.count)))
            return PlanStats(rc, src.columns)
        if isinstance(node, EnforceSingleRowNode):
            return PlanStats(1.0)
        if isinstance(node, UnionNode):
            rcs = [self.stats(i).row_count for i in node.inputs]
            if any(r is None for r in rcs):
                return PlanStats(None)
            return PlanStats(float(sum(rcs)))
        if isinstance(node, UnnestNode):
            src = self.stats(node.source)
            rc = None if src.row_count is None else src.row_count * 3.0
            return PlanStats(rc)
        if isinstance(node, OutputNode):
            return self.stats(node.source)
        if isinstance(node, (RemoteSourceNode, RemoteMergeNode)):
            return PlanStats(None)
        return PlanStats(None)

    def _scan_stats(self, node: TableScanNode) -> PlanStats:
        if self.metadata is None:
            return PlanStats(None)
        try:
            conn = self.metadata.registry.get(node.catalog)
            handle = conn.get_table(node.table)
            ts = conn.table_statistics(handle)
        except Exception:
            return PlanStats(None)
        if ts is None:
            return PlanStats(None)
        cols: Dict[int, ColumnStats] = {}
        for ch, name in enumerate(node.column_names):
            cs = ColumnStats(
                ndv=ts.ndv.get(name),
                nulls_fraction=ts.nulls_fraction.get(name, 0.0),
                low=_as_number(ts.low.get(name)),
                high=_as_number(ts.high.get(name)))
            if cs.ndv is not None or cs.low is not None:
                cols[ch] = cs
        return PlanStats(float(ts.row_count), cols)

    # -- filters --------------------------------------------------------
    def _filter_stats(self, node: FilterNode) -> PlanStats:
        src = self.stats(node.source)
        if src.row_count is None:
            return PlanStats(None)
        sel, narrowed = _selectivity(node.predicate, src)
        rc = src.row_count * sel
        cols = dict(src.columns)
        cols.update(narrowed)
        # NDV cannot exceed the remaining row count
        cols = {ch: ColumnStats(
            None if c.ndv is None else min(c.ndv, rc),
            c.nulls_fraction, c.low, c.high) for ch, c in cols.items()}
        return PlanStats(rc, cols)

    def _project_stats(self, node: ProjectNode) -> PlanStats:
        src = self.stats(node.source)
        if src.row_count is None:
            return PlanStats(None)
        cols: Dict[int, ColumnStats] = {}
        for i, e in enumerate(node.expressions):
            if isinstance(e, InputRef) and e.index in src.columns:
                cols[i] = src.columns[e.index]
        return PlanStats(src.row_count, cols)

    def _agg_stats(self, node: AggregationNode) -> PlanStats:
        src = self.stats(node.source)
        if src.row_count is None:
            return PlanStats(None)
        if not node.group_channels:
            return PlanStats(1.0)
        groups = 1.0
        known = True
        for ch in node.group_channels:
            ndv = src.col(ch).ndv
            if ndv is None:
                known = False
                break
            groups *= max(ndv, 1.0)
        if not known:
            # the reference falls back to input rows when key NDV is
            # unknown; a 0.1 dampening matches its default heuristics
            groups = src.row_count * 0.1
        rc = min(groups, src.row_count)
        cols = {i: src.col(ch)
                for i, ch in enumerate(node.group_channels)}
        return PlanStats(rc, cols)

    # -- joins ----------------------------------------------------------
    def _join_stats(self, node: JoinNode) -> PlanStats:
        left = self.stats(node.left)
        right = self.stats(node.right)
        if left.row_count is None or right.row_count is None:
            return PlanStats(None)
        nleft = len(node.left.columns)
        if node.kind == "cross" or not node.left_keys:
            rc = left.row_count * right.row_count
        else:
            rc = left.row_count * right.row_count
            for lk, rk in zip(node.left_keys, node.right_keys):
                ndv_l = left.col(lk).ndv
                ndv_r = right.col(rk).ndv
                denom = None
                if ndv_l is not None and ndv_r is not None:
                    denom = max(ndv_l, ndv_r)
                elif ndv_l is not None:
                    denom = ndv_l
                elif ndv_r is not None:
                    denom = ndv_r
                if denom is not None and denom > 0:
                    rc /= denom
                else:
                    rc *= 0.1  # unknown key NDV: damp, don't explode
            if node.kind == "left":
                rc = max(rc, left.row_count)
        if node.residual is not None:
            rc *= UNKNOWN_FILTER_COEFFICIENT
        cols = dict(left.columns)
        for ch, cs in right.columns.items():
            cols[nleft + ch] = cs
        return PlanStats(rc, cols)

    def _semijoin_stats(self, node: SemiJoinNode) -> PlanStats:
        src = self.stats(node.source)
        filt = self.stats(node.filtering)
        if src.row_count is None:
            return PlanStats(None)
        # SemiJoinStatsCalculator: matched fraction ~ ndv overlap
        sel = 0.5
        if filt.row_count is not None and node.source_keys:
            ndv_s = src.col(node.source_keys[0]).ndv
            ndv_f = filt.col(node.filtering_keys[0]).ndv
            if ndv_s and ndv_f:
                sel = min(1.0, ndv_f / max(ndv_s, 1.0))
        if node.negated:
            sel = 1.0 - sel
        return PlanStats(src.row_count * max(sel, 0.0), src.columns)


# ---------------------------------------------------------------------------
# predicate selectivity (FilterStatsCalculator role)
# ---------------------------------------------------------------------------

_CMP = {"eq", "ne", "lt", "le", "gt", "ge"}


def _selectivity(expr: RowExpression, src: PlanStats
                 ) -> Tuple[float, Dict[int, ColumnStats]]:
    """Returns (selectivity in [0,1], narrowed per-channel stats)."""
    if isinstance(expr, SpecialForm):
        if expr.form == "AND":
            sel = 1.0
            narrowed: Dict[int, ColumnStats] = {}
            cur = src
            for a in expr.args:
                s, n = _selectivity(a, cur)
                sel *= s
                narrowed.update(n)
                cur = PlanStats(cur.row_count,
                                {**cur.columns, **narrowed})
            return sel, narrowed
        if expr.form == "OR":
            inv = 1.0
            for a in expr.args:
                s, _ = _selectivity(a, src)
                inv *= (1.0 - s)
            return 1.0 - inv, {}
        if expr.form == "IN":
            v = expr.args[0]
            if isinstance(v, InputRef):
                ndv = src.col(v.index).ndv
                if ndv:
                    return min(1.0, (len(expr.args) - 1) / ndv), {}
            return 0.5, {}
    if isinstance(expr, Call) and expr.name in _CMP and len(expr.args) == 2:
        return _comparison_selectivity(expr, src)
    if isinstance(expr, Call) and expr.name == "not" and len(expr.args) == 1:
        s, _ = _selectivity(expr.args[0], src)
        return 1.0 - s, {}
    if isinstance(expr, Call) and getattr(expr.fn, "null_mode", None) \
            == "is_null" and expr.args:
        a = expr.args[0]
        if isinstance(a, InputRef):
            return src.col(a.index).nulls_fraction, {}
        return 0.1, {}
    if isinstance(expr, Call) and getattr(expr.fn, "null_mode", None) \
            == "is_not_null" and expr.args:
        a = expr.args[0]
        if isinstance(a, InputRef):
            return 1.0 - src.col(a.index).nulls_fraction, {}
        return 0.9, {}
    if isinstance(expr, Constant):
        if expr.value is True:
            return 1.0, {}
        if expr.value in (False, None):
            return 0.0, {}
    return UNKNOWN_FILTER_COEFFICIENT, {}


def _comparison_selectivity(expr: Call, src: PlanStats
                            ) -> Tuple[float, Dict[int, ColumnStats]]:
    a, b = expr.args
    op = expr.name
    if isinstance(b, InputRef) and isinstance(a, Constant):
        a, b = b, a
        op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(op, op)
    if not (isinstance(a, InputRef) and isinstance(b, Constant)):
        if (isinstance(a, InputRef) and isinstance(b, InputRef)
                and op == "eq"):
            ndv_a = src.col(a.index).ndv
            ndv_b = src.col(b.index).ndv
            ndv = max(filter(None, [ndv_a, ndv_b]), default=None)
            return (1.0 / ndv if ndv else UNKNOWN_FILTER_COEFFICIENT), {}
        return UNKNOWN_FILTER_COEFFICIENT, {}
    cs = src.col(a.index)
    lit = _as_number(b.value)
    if op == "eq":
        sel = 1.0 / cs.ndv if cs.ndv else 0.1
        narrowed = ColumnStats(1.0, 0.0, lit, lit)
        return min(sel, 1.0), {a.index: narrowed}
    if op == "ne":
        sel = 1.0 - (1.0 / cs.ndv if cs.ndv else 0.1)
        return max(sel, 0.0), {}
    if lit is None or cs.low is None or cs.high is None \
            or cs.high <= cs.low:
        return 0.3, {}  # range comparison without domain: Presto's default
    span = cs.high - cs.low
    frac_below = min(max((lit - cs.low) / span, 0.0), 1.0)
    if op in ("lt", "le"):
        sel = frac_below
        narrowed = ColumnStats(
            None if cs.ndv is None else cs.ndv * max(sel, 1e-9),
            0.0, cs.low, lit)
    else:
        sel = 1.0 - frac_below
        narrowed = ColumnStats(
            None if cs.ndv is None else cs.ndv * max(sel, 1e-9),
            0.0, lit, cs.high)
    sel *= (1.0 - cs.nulls_fraction)
    return min(max(sel, 0.0), 1.0), {a.index: narrowed}
