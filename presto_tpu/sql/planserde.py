"""JSON serde for plan fragments (the task-create wire format).

The reference ships plan fragments to workers as JSON inside
TaskUpdateRequest (presto-main/.../server/TaskUpdateRequest.java, posted by
HttpRemoteTask.java:100 and decoded by TaskResource.java:121) — never as
serialized Java objects.  This module is the same contract for our plan IR:
a self-describing JSON tree, decoded by re-resolving function bindings
against the registry (expr/functions.py), so nothing executable ever rides
the wire and task create is safe against untrusted bodies.

Types are encoded by their canonical display form and decoded with
``types.parse_type``; Constants are already in storage domain (ints,
floats, strings, bools, None), which is exactly JSON's value space.
"""

from __future__ import annotations

from typing import Any, Dict, List

from presto_tpu import types as T
from presto_tpu.expr import functions as F
from presto_tpu.expr.functions import AggSpec
from presto_tpu.expr.ir import (
    Call, Constant, InputRef, LambdaExpr, RowExpression, SpecialForm, VarRef,
)
from presto_tpu.server.fragmenter import PlanFragment
from presto_tpu.sql.plan import (
    AggregationNode, EnforceSingleRowNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanAggregate, PlanNode, PlanWindowFunction, ProjectNode,
    RemoteMergeNode, RemoteSourceNode, SemiJoinNode, SortNode,
    TableFinishNode, TableWriterNode,
    TableScanNode, UnionNode,
    UnnestNode, ValuesNode, WindowNode,
)


class PlanSerdeError(ValueError):
    pass


# --------------------------------------------------------------------------
# Types and columns
# --------------------------------------------------------------------------

def _ty(t: T.Type) -> str:
    return t.display()


def _unty(s: str) -> T.Type:
    return T.parse_type(s)


def _cols(cols) -> List[List[str]]:
    return [[n, _ty(t)] for n, t in cols]


def _uncols(cols):
    return tuple((n, _unty(t)) for n, t in cols)


# --------------------------------------------------------------------------
# Expressions
# --------------------------------------------------------------------------

_JSON_SCALARS = (bool, int, float, str, type(None))


def expr_to_json(e: RowExpression) -> Dict[str, Any]:
    if isinstance(e, InputRef):
        return {"k": "ref", "i": e.index, "t": _ty(e.type)}
    if isinstance(e, Constant):
        if not isinstance(e.value, _JSON_SCALARS):
            raise PlanSerdeError(
                f"non-JSON constant {e.value!r} of type {e.type.display()}")
        return {"k": "const", "v": e.value, "t": _ty(e.type)}
    if isinstance(e, Call):
        out = {"k": "call", "name": e.name,
               "args": [expr_to_json(a) for a in e.args], "t": _ty(e.type)}
        # round() bakes the digit count into the bound impl (build.py
        # round_digits); recover it from the resolution key for rebinding.
        if e.name == "round" and getattr(e.fn, "re_key", None):
            out["digits"] = e.fn.re_key[2]
        if e.name == "row_field" and getattr(e.fn, "re_key", None):
            out["field"] = e.fn.re_key[2]
        if e.name in ("date_format", "format_datetime") \
                and getattr(e.fn, "re_key", None):
            out["fmt"] = e.fn.re_key[2]
        return out
    if isinstance(e, SpecialForm):
        return {"k": "form", "form": e.form,
                "args": [expr_to_json(a) for a in e.args], "t": _ty(e.type)}
    if isinstance(e, VarRef):
        return {"k": "var", "name": e.name, "t": _ty(e.type)}
    if isinstance(e, LambdaExpr):
        return {"k": "lambda", "params": list(e.params),
                "ptypes": [_ty(p) for p in e.param_types],
                "body": expr_to_json(e.body), "t": _ty(e.type)}
    raise PlanSerdeError(f"unknown expression {type(e).__name__}")


def expr_from_json(d: Dict[str, Any]) -> RowExpression:
    k = d["k"]
    t = _unty(d["t"])
    if k == "ref":
        return InputRef(int(d["i"]), t)
    if k == "const":
        v = d["v"]
        if not isinstance(v, _JSON_SCALARS):
            raise PlanSerdeError(f"bad constant {v!r}")
        return Constant(v, t)
    if k == "call":
        args = tuple(expr_from_json(a) for a in d["args"])
        name = d["name"]
        if name == "cast":
            fn = F.resolve_cast(args[0].type, t)
        elif name == "try_cast":
            fn = F.resolve_try_cast(args[0].type, t)
        elif name == "round":
            fn = F.resolve_round(args[0].type, int(d.get("digits", 0)))
        elif name == "row_field":
            fn = F.resolve_row_field_index(args[0].type, int(d["field"]))
        elif name == "$array":
            fn = F.resolve_array_constructor(t, len(args))
        elif name == "date_format":
            fn = F.resolve_date_format(args[0].type, str(d["fmt"]))
        elif name == "format_datetime":
            fn = F.resolve_format_datetime(args[0].type, str(d["fmt"]))
        else:
            fn = F.resolve_scalar(name, [a.type for a in args])
        return Call(name, args, t, fn)
    if k == "form":
        return SpecialForm(str(d["form"]),
                           tuple(expr_from_json(a) for a in d["args"]), t)
    if k == "var":
        return VarRef(str(d["name"]), t)
    if k == "lambda":
        return LambdaExpr(tuple(d["params"]),
                          tuple(_unty(p) for p in d["ptypes"]),
                          expr_from_json(d["body"]), t)
    raise PlanSerdeError(f"unknown expression kind {k!r}")


# --------------------------------------------------------------------------
# Aggregates / window functions
# --------------------------------------------------------------------------

def _agg_to_json(a: PlanAggregate) -> Dict[str, Any]:
    s = a.spec
    return {"spec": {"name": s.name,
                     "arg_type": None if s.arg_type is None else _ty(s.arg_type),
                     "result_type": _ty(s.result_type),
                     "components": [[p, _ty(ct)] for p, ct in s.components],
                     "finalize": s.finalize},
            "channel": a.channel, "distinct": a.distinct,
            "output_name": a.output_name}


def _agg_from_json(d: Dict[str, Any]) -> PlanAggregate:
    s = d["spec"]
    spec = AggSpec(
        s["name"],
        None if s["arg_type"] is None else _unty(s["arg_type"]),
        _unty(s["result_type"]),
        [(p, _unty(ct)) for p, ct in s["components"]],
        s.get("finalize", "identity"))
    return PlanAggregate(spec, d["channel"], d.get("distinct", False),
                         d.get("output_name", ""))


def _winfn_to_json(f: PlanWindowFunction) -> Dict[str, Any]:
    return {"name": f.name, "arg_channels": list(f.arg_channels),
            "result_type": _ty(f.result_type), "frame_unit": f.frame_unit,
            "frame_start": f.frame_start, "frame_end": f.frame_end,
            "frame_start_offset": f.frame_start_offset,
            "frame_end_offset": f.frame_end_offset, "offset": f.offset,
            "default_channel": f.default_channel}


def _winfn_from_json(d: Dict[str, Any]) -> PlanWindowFunction:
    return PlanWindowFunction(
        d["name"], tuple(d["arg_channels"]), _unty(d["result_type"]),
        d.get("frame_unit", "range"),
        d.get("frame_start", "unbounded_preceding"),
        d.get("frame_end", "current"), d.get("frame_start_offset"),
        d.get("frame_end_offset"), d.get("offset"), d.get("default_channel"))


# --------------------------------------------------------------------------
# Plan nodes
# --------------------------------------------------------------------------

def _keys3(keys):
    # (channel, ascending, nulls_first) triples
    return [[c, a, nf] for c, a, nf in keys]


def _unkeys3(keys):
    return tuple((int(c), bool(a), nf) for c, a, nf in keys)


def node_to_json(n: PlanNode) -> Dict[str, Any]:
    if isinstance(n, TableScanNode):
        return {"k": "scan", "catalog": n.catalog, "table": n.table,
                "column_names": list(n.column_names),
                "columns": _cols(n.columns)}
    if isinstance(n, ValuesNode):
        for row in n.rows:
            for v in row:
                if not isinstance(v, _JSON_SCALARS):
                    raise PlanSerdeError(f"non-JSON values literal {v!r}")
        return {"k": "values", "columns": _cols(n.columns),
                "rows": [list(r) for r in n.rows]}
    if isinstance(n, FilterNode):
        return {"k": "filter", "source": node_to_json(n.source),
                "predicate": expr_to_json(n.predicate)}
    if isinstance(n, ProjectNode):
        return {"k": "project", "source": node_to_json(n.source),
                "expressions": [expr_to_json(e) for e in n.expressions],
                "columns": _cols(n.columns)}
    if isinstance(n, AggregationNode):
        return {"k": "agg", "source": node_to_json(n.source),
                "group_channels": list(n.group_channels),
                "aggregates": [_agg_to_json(a) for a in n.aggregates],
                "columns": _cols(n.columns), "step": n.step}
    if isinstance(n, JoinNode):
        return {"k": "join", "kind": n.kind,
                "left": node_to_json(n.left), "right": node_to_json(n.right),
                "left_keys": list(n.left_keys),
                "right_keys": list(n.right_keys),
                "columns": _cols(n.columns),
                "residual": None if n.residual is None
                else expr_to_json(n.residual),
                "distribution": n.distribution}
    if isinstance(n, TableWriterNode):
        return {"k": "tablewriter", "source": node_to_json(n.source),
                "catalog": n.catalog, "table": n.table,
                "write_id": n.write_id, "columns": _cols(n.columns)}
    if isinstance(n, TableFinishNode):
        return {"k": "tablefinish", "source": node_to_json(n.source),
                "catalog": n.catalog, "table": n.table,
                "write_id": n.write_id, "columns": _cols(n.columns)}
    if isinstance(n, SemiJoinNode):
        return {"k": "semijoin", "source": node_to_json(n.source),
                "filtering": node_to_json(n.filtering),
                "source_keys": list(n.source_keys),
                "filtering_keys": list(n.filtering_keys),
                "negated": n.negated,
                "residual": None if n.residual is None
                else expr_to_json(n.residual),
                "null_aware": n.null_aware}
    if isinstance(n, WindowNode):
        return {"k": "window", "source": node_to_json(n.source),
                "partition_channels": list(n.partition_channels),
                "order_keys": _keys3(n.order_keys),
                "functions": [_winfn_to_json(f) for f in n.functions],
                "columns": _cols(n.columns)}
    if isinstance(n, UnionNode):
        return {"k": "union",
                "inputs": [node_to_json(i) for i in n.inputs],
                "columns": _cols(n.columns)}
    if isinstance(n, SortNode):
        return {"k": "sort", "source": node_to_json(n.source),
                "sort_keys": _keys3(n.sort_keys)}
    if isinstance(n, LimitNode):
        return {"k": "limit", "source": node_to_json(n.source),
                "count": n.count}
    if isinstance(n, EnforceSingleRowNode):
        return {"k": "single_row", "source": node_to_json(n.source)}
    if isinstance(n, UnnestNode):
        return {"k": "unnest", "source": node_to_json(n.source),
                "replicate_channels": list(n.replicate_channels),
                "unnest_channels": list(n.unnest_channels),
                "ordinality": n.ordinality, "outer": n.outer,
                "columns": _cols(n.columns)}
    if isinstance(n, RemoteSourceNode):
        return {"k": "remote", "fragment_ids": list(n.fragment_ids),
                "columns": _cols(n.columns)}
    if isinstance(n, RemoteMergeNode):
        return {"k": "remote_merge", "fragment_ids": list(n.fragment_ids),
                "sort_keys": _keys3(n.sort_keys),
                "columns": _cols(n.columns), "limit": n.limit}
    if isinstance(n, OutputNode):
        return {"k": "output", "source": node_to_json(n.source),
                "columns": _cols(n.columns)}
    raise PlanSerdeError(f"unknown plan node {type(n).__name__}")


def node_from_json(d: Dict[str, Any]) -> PlanNode:
    k = d["k"]
    if k == "scan":
        return TableScanNode(d["catalog"], d["table"],
                             tuple(d["column_names"]), _uncols(d["columns"]))
    if k == "values":
        return ValuesNode(_uncols(d["columns"]),
                          tuple(tuple(r) for r in d["rows"]))
    if k == "filter":
        return FilterNode(node_from_json(d["source"]),
                          expr_from_json(d["predicate"]))
    if k == "project":
        return ProjectNode(node_from_json(d["source"]),
                           tuple(expr_from_json(e) for e in d["expressions"]),
                           _uncols(d["columns"]))
    if k == "agg":
        return AggregationNode(node_from_json(d["source"]),
                               tuple(d["group_channels"]),
                               tuple(_agg_from_json(a)
                                     for a in d["aggregates"]),
                               _uncols(d["columns"]), d.get("step", "single"))
    if k == "join":
        return JoinNode(d["kind"], node_from_json(d["left"]),
                        node_from_json(d["right"]), tuple(d["left_keys"]),
                        tuple(d["right_keys"]), _uncols(d["columns"]),
                        None if d.get("residual") is None
                        else expr_from_json(d["residual"]),
                        d.get("distribution"))
    if k == "tablewriter":
        return TableWriterNode(node_from_json(d["source"]), d["catalog"],
                               d["table"], d["write_id"],
                               _uncols(d["columns"]))
    if k == "tablefinish":
        return TableFinishNode(node_from_json(d["source"]), d["catalog"],
                               d["table"], d["write_id"],
                               _uncols(d["columns"]))
    if k == "semijoin":
        return SemiJoinNode(node_from_json(d["source"]),
                            node_from_json(d["filtering"]),
                            tuple(d["source_keys"]),
                            tuple(d["filtering_keys"]),
                            d.get("negated", False),
                            None if d.get("residual") is None
                            else expr_from_json(d["residual"]),
                            d.get("null_aware", False))
    if k == "window":
        return WindowNode(node_from_json(d["source"]),
                          tuple(d["partition_channels"]),
                          _unkeys3(d["order_keys"]),
                          tuple(_winfn_from_json(f) for f in d["functions"]),
                          _uncols(d["columns"]))
    if k == "union":
        return UnionNode(tuple(node_from_json(i) for i in d["inputs"]),
                         _uncols(d["columns"]))
    if k == "sort":
        return SortNode(node_from_json(d["source"]),
                        _unkeys3(d["sort_keys"]))
    if k == "limit":
        return LimitNode(node_from_json(d["source"]), int(d["count"]))
    if k == "single_row":
        return EnforceSingleRowNode(node_from_json(d["source"]))
    if k == "unnest":
        return UnnestNode(node_from_json(d["source"]),
                          tuple(d["replicate_channels"]),
                          tuple(d["unnest_channels"]),
                          bool(d["ordinality"]), _uncols(d["columns"]),
                          outer=bool(d.get("outer", False)))
    if k == "remote":
        return RemoteSourceNode(tuple(d["fragment_ids"]),
                                _uncols(d["columns"]))
    if k == "remote_merge":
        return RemoteMergeNode(tuple(d["fragment_ids"]),
                               _unkeys3(d["sort_keys"]),
                               _uncols(d["columns"]),
                               d.get("limit"))
    if k == "output":
        return OutputNode(node_from_json(d["source"]), _uncols(d["columns"]))
    raise PlanSerdeError(f"unknown plan node kind {k!r}")


# --------------------------------------------------------------------------
# Fragments
# --------------------------------------------------------------------------

def fragment_to_json(f: PlanFragment) -> Dict[str, Any]:
    kind, channels = f.output_partitioning
    return {"fragment_id": f.fragment_id, "root": node_to_json(f.root),
            "partitioning": f.partitioning,
            "output_partitioning": [kind, list(channels)],
            "consumed_fragments": list(f.consumed_fragments),
            "scale_rows": f.scale_rows,
            "producer_subtree": list(f.producer_subtree),
            "device_exchange_eligible": f.device_exchange_eligible}


def fragment_from_json(d: Dict[str, Any]) -> PlanFragment:
    kind, channels = d["output_partitioning"]
    return PlanFragment(int(d["fragment_id"]), node_from_json(d["root"]),
                        str(d["partitioning"]), (str(kind), tuple(channels)),
                        tuple(d["consumed_fragments"]),
                        d.get("scale_rows"),
                        producer_subtree=tuple(
                            d.get("producer_subtree") or ()),
                        device_exchange_eligible=d.get(
                            "device_exchange_eligible"))


# --------------------------------------------------------------------------
# Whole distributed plans (the coordinator-HA journal format)
# --------------------------------------------------------------------------

def dplan_to_json(dplan) -> Dict[str, Any]:
    """Serde the coordinator's fragmented plan for the durable
    query-state journal (server/statestore.py): fragments ride the SAME
    JSON contract task create uses, so a standby coordinator re-creates
    tasks from the journal with byte-identical bodies."""
    return {
        "fragments": [fragment_to_json(f) for f in dplan.fragments],
        "root_fragment_id": dplan.root_fragment_id,
        "column_names": list(dplan.column_names),
        "column_types": [t.display() for t in dplan.column_types],
    }


def dplan_from_json(d: Dict[str, Any]):
    from presto_tpu.server.fragmenter import DistributedPlan

    return DistributedPlan(
        [fragment_from_json(f) for f in d["fragments"]],
        int(d["root_fragment_id"]),
        [str(n) for n in d["column_names"]],
        [T.parse_type(s) for s in d["column_types"]])
