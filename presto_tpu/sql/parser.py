"""Recursive-descent SQL parser.

Covers the query shape of SqlBase.g4 that the engine executes: SELECT
[DISTINCT] items FROM relations (comma / JOIN ... ON) WHERE ... GROUP BY
... HAVING ... ORDER BY ... LIMIT n, WITH ctes, subqueries (FROM,
IN/EXISTS/scalar), CASE, CAST, EXTRACT, BETWEEN, LIKE, interval & typed
literals, EXPLAIN, SHOW TABLES/COLUMNS.  Operator precedence mirrors the
reference grammar: OR < AND < NOT < predicate < additive < multiplicative
< unary < primary.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from presto_tpu.sql import tree as t
from presto_tpu.sql.lexer import SqlSyntaxError, Token, tokenize


def parse_statement(sql: str) -> t.Node:
    return _Parser(tokenize(sql), sql).parse_statement()


def parse_expression(sql: str) -> t.Expression:
    p = _Parser(tokenize(sql))
    e = p.expression()
    p.expect_eof()
    return e


class _Parser:
    def __init__(self, tokens: List[Token], sql: str = ""):
        self.toks = tokens
        self.pos = 0
        self.sql = sql
        self._param_seq = 0

    # --- token helpers -----------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.pos + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        tok = self.toks[self.pos]
        if tok.kind != "EOF":
            self.pos += 1
        return tok

    def at_kw(self, *words: str) -> bool:
        tok = self.peek()
        return tok.kind == "KEYWORD" and tok.text in words

    def at_op(self, *ops: str) -> bool:
        tok = self.peek()
        return tok.kind == "OP" and tok.text in ops

    def accept_kw(self, *words: str) -> Optional[str]:
        if self.at_kw(*words):
            return self.next().text
        return None

    def accept_op(self, *ops: str) -> Optional[str]:
        if self.at_op(*ops):
            return self.next().text
        return None

    def expect_kw(self, word: str) -> None:
        tok = self.next()
        if tok.kind != "KEYWORD" or tok.text != word:
            raise SqlSyntaxError(f"expected {word.upper()}, found "
                                 f"{tok.text or 'end of input'!r}",
                                 tok.line, tok.col)

    def expect_op(self, op: str) -> None:
        tok = self.next()
        if tok.kind != "OP" or tok.text != op:
            raise SqlSyntaxError(f"expected {op!r}, found "
                                 f"{tok.text or 'end of input'!r}",
                                 tok.line, tok.col)

    # Soft (context-sensitive) keywords: words like DELETE/PREPARE/USE are
    # only keywords in statement position; the lexer tokenizes them as
    # IDENT, so these helpers match by text regardless of token kind
    # (SqlBase.g4's nonReserved rule plays the same role).
    def at_word(self, *words: str) -> bool:
        tok = self.peek()
        return tok.kind in ("KEYWORD", "IDENT") and tok.text in words

    def at_word_seq(self, *words: str) -> bool:
        for k, w in enumerate(words):
            tok = self.peek(k)
            if tok.kind not in ("KEYWORD", "IDENT") or tok.text != w:
                return False
        return True

    def accept_word(self, *words: str) -> Optional[str]:
        if self.at_word(*words):
            return self.next().text
        return None

    def expect_word(self, word: str) -> None:
        tok = self.next()
        if tok.kind not in ("KEYWORD", "IDENT") or tok.text != word:
            raise SqlSyntaxError(f"expected {word.upper()}, found "
                                 f"{tok.text or 'end of input'!r}",
                                 tok.line, tok.col)

    def expect_eof(self) -> None:
        tok = self.peek()
        if tok.kind != "EOF":
            raise SqlSyntaxError(f"unexpected {tok.text!r}", tok.line,
                                 tok.col)

    def identifier(self) -> str:
        tok = self.next()
        if tok.kind in ("IDENT", "QIDENT"):
            return tok.text
        # non-reserved keywords usable as identifiers
        if tok.kind == "KEYWORD" and tok.text in (
                "year", "month", "day", "hour", "minute", "second", "date",
                "time", "first", "last", "tables", "columns", "show"):
            return tok.text
        raise SqlSyntaxError(f"expected identifier, found "
                             f"{tok.text or 'end of input'!r}",
                             tok.line, tok.col)

    def qualified_name(self) -> Tuple[str, ...]:
        parts = [self.identifier()]
        while self.at_op("."):
            self.next()
            parts.append(self.identifier())
        return tuple(parts)

    # --- statements --------------------------------------------------------
    def parse_statement(self) -> t.Node:
        if self.accept_kw("call"):
            name = self.qualified_name()
            self.expect_op("(")
            args: List[t.Expression] = []
            if not self.at_op(")"):
                args.append(self.expression())
                while self.accept_op(","):
                    args.append(self.expression())
            self.expect_op(")")
            self.accept_op(";")
            self.expect_eof()
            return t.CallProcedure(name, tuple(args))
        if self.accept_kw("explain"):
            plan_type = "logical"
            if self.at_op("(") and self.peek(1).text == "type":
                self.next()
                self.expect_word("type")
                tok = self.next()
                if tok.text not in ("logical", "distributed", "validate",
                                    "io"):
                    raise SqlSyntaxError(
                        f"unknown EXPLAIN type {tok.text!r}",
                        tok.line, tok.col)
                plan_type = tok.text
                self.expect_op(")")
            analyze = bool(self.accept_kw("analyze"))
            inner = self.parse_statement()
            return t.Explain(inner, analyze, plan_type)
        if self.accept_kw("create"):
            replace = False
            if self.accept_kw("or"):
                self.expect_word("replace")
                replace = True
            if self.accept_word("view"):
                name = self.qualified_name()
                self.expect_kw("as")
                start = self.pos
                q = self.query()
                node: t.Node = t.CreateView(
                    name, q, replace,
                    original_sql=self._text_between(start, self.pos))
                self.accept_op(";")
                self.expect_eof()
                return node
            if replace:
                raise SqlSyntaxError("OR REPLACE only applies to views",
                                     self.peek().line, self.peek().col)
            self.expect_kw("table")
            if_not_exists = False
            if self.accept_word("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            name = self.qualified_name()
            if self.accept_kw("as"):
                node = t.CreateTableAs(name, self.query(), if_not_exists)
            elif self.at_kw("with"):
                node = t.CreateTableAs(
                    name, None, if_not_exists,      # query filled below
                    self.table_properties())
                self.expect_kw("as")
                node = dataclasses.replace(node, query=self.query())
            else:
                self.expect_op("(")
                cols = [(self.identifier(), self.type_name())]
                while self.accept_op(","):
                    cols.append((self.identifier(), self.type_name()))
                self.expect_op(")")
                props = self.table_properties() if self.at_kw("with") \
                    else ()
                node = t.CreateTable(name, tuple(cols), if_not_exists,
                                     props)
            self.accept_op(";")
            self.expect_eof()
            return node
        if self.accept_kw("insert"):
            self.expect_kw("into")
            name = self.qualified_name()
            cols: Tuple[str, ...] = ()
            if self.at_op("(") and not (
                    self.peek(1).kind == "KEYWORD"
                    and self.peek(1).text in ("select", "with", "values")):
                self.next()
                names = [self.identifier()]
                while self.accept_op(","):
                    names.append(self.identifier())
                self.expect_op(")")
                cols = tuple(names)
            if self.at_kw("values"):
                source: t.Node = self.inline_values()
            else:
                source = self.query()
            self.accept_op(";")
            self.expect_eof()
            return t.Insert(name, cols, source)
        if self.accept_kw("drop"):
            is_view = bool(self.accept_word("view"))
            if not is_view:
                self.expect_kw("table")
            if_exists = False
            if self.accept_word("if"):
                self.expect_kw("exists")
                if_exists = True
            name = self.qualified_name()
            self.accept_op(";")
            self.expect_eof()
            return (t.DropView(name, if_exists) if is_view
                    else t.DropTable(name, if_exists))
        if self.accept_word("delete"):
            self.expect_kw("from")
            name = self.qualified_name()
            where = self.expression() if self.accept_kw("where") else None
            self.accept_op(";")
            self.expect_eof()
            return t.Delete(name, where)
        if self.accept_word("alter"):
            self.expect_kw("table")
            name = self.qualified_name()
            self.expect_word("rename")
            self.expect_word("to")
            new_name = self.qualified_name()
            self.accept_op(";")
            self.expect_eof()
            return t.RenameTable(name, new_name)
        if self.accept_word("prepare"):
            name = self.identifier()
            self.expect_kw("from")
            start = self.pos
            inner = self.parse_statement()
            return t.Prepare(name, inner,
                             self._text_between(start, len(self.toks)))
        if (self.at_word("execute")
                and self.peek(1).kind in ("IDENT", "QIDENT")):
            self.next()
            name = self.identifier()
            params: List[t.Expression] = []
            if self.accept_word("using"):
                params.append(self.expression())
                while self.accept_op(","):
                    params.append(self.expression())
            self.accept_op(";")
            self.expect_eof()
            return t.ExecutePrepared(name, tuple(params))
        if self.accept_word("deallocate"):
            self.expect_word("prepare")
            name = self.identifier()
            self.accept_op(";")
            self.expect_eof()
            return t.Deallocate(name)
        if self.accept_word("describe"):
            if self.accept_word("input"):
                node = t.DescribeInput(self.identifier())
            elif self.accept_word("output"):
                node = t.DescribeOutput(self.identifier())
            else:
                node = t.ShowColumns(self.qualified_name())
            self.accept_op(";")
            self.expect_eof()
            return node
        if self.accept_word("use"):
            parts = self.qualified_name()
            if len(parts) > 2:
                raise SqlSyntaxError("USE catalog[.schema]",
                                     self.peek().line, self.peek().col)
            self.accept_op(";")
            self.expect_eof()
            return t.Use(parts[0], parts[1] if len(parts) > 1 else None)
        if self.at_word("start"):
            self.next()
            self.expect_word("transaction")
            self.accept_op(";")
            self.expect_eof()
            return t.StartTransaction()
        if self.accept_word("commit"):
            self.accept_word("work")
            self.accept_op(";")
            self.expect_eof()
            return t.Commit()
        if self.accept_word("rollback"):
            self.accept_word("work")
            self.accept_op(";")
            self.expect_eof()
            return t.Rollback()
        if self.at_kw("analyze"):
            self.next()
            name = self.qualified_name()
            self.accept_op(";")
            self.expect_eof()
            return t.Analyze(name)
        if self.at_word("grant") or self.at_word("revoke"):
            is_grant = self.next().text == "grant"
            privs = [self.privilege()]
            while self.accept_op(","):
                privs.append(self.privilege())
            self.expect_kw("on")
            self.accept_kw("table")
            name = self.qualified_name()
            self.expect_word("to" if is_grant else "from")
            grantee = self.identifier()
            self.accept_op(";")
            self.expect_eof()
            return (t.Grant(tuple(privs), name, grantee) if is_grant
                    else t.Revoke(tuple(privs), name, grantee))
        if self.accept_kw("set"):
            self.expect_kw("session")
            name = ".".join(self.qualified_name())
            self.expect_op("=")
            tok = self.next()
            if tok.kind not in ("STRING", "NUMBER", "KEYWORD", "IDENT"):
                raise SqlSyntaxError("expected session property value",
                                     tok.line, tok.col)
            self.accept_op(";")
            self.expect_eof()
            return t.SetSession(name, tok.text)
        if self.accept_kw("reset"):
            self.expect_kw("session")
            name = ".".join(self.qualified_name())
            self.accept_op(";")
            self.expect_eof()
            return t.ResetSession(name)
        if self.accept_kw("show"):
            if self.accept_kw("tables"):
                cat = None
                if self.accept_kw("from") or self.accept_kw("in"):
                    cat = self.identifier()
                node: t.Node = t.ShowTables(cat, self._opt_like())
            elif self.accept_kw("session"):
                node = t.ShowSession()
            elif self.accept_word("catalogs"):
                node = t.ShowCatalogs(self._opt_like())
            elif self.accept_word("schemas"):
                cat = None
                if self.accept_kw("from") or self.accept_kw("in"):
                    cat = self.identifier()
                node = t.ShowSchemas(cat, self._opt_like())
            elif self.accept_word("functions"):
                node = t.ShowFunctions(self._opt_like())
            elif self.accept_word("stats"):
                self.expect_kw("for")
                node = t.ShowStats(self.qualified_name())
            elif self.accept_kw("create"):
                if self.accept_word("view"):
                    node = t.ShowCreateView(self.qualified_name())
                else:
                    self.expect_kw("table")
                    node = t.ShowCreateTable(self.qualified_name())
            else:
                self.expect_kw("columns")
                self.expect_kw("from")
                node = t.ShowColumns(self.qualified_name())
            self.accept_op(";")
            self.expect_eof()
            return node
        q = self.query()
        self.accept_op(";")
        self.expect_eof()
        return q

    def query(self) -> t.Node:
        with_queries: List[Tuple[str, t.Query]] = []
        if self.accept_kw("with"):
            while True:
                name = self.identifier()
                self.expect_kw("as")
                self.expect_op("(")
                with_queries.append((name, self.query()))
                self.expect_op(")")
                if not self.accept_op(","):
                    break
        body = self.query_expr()
        if isinstance(body, t.Query):
            return dataclasses.replace(
                body, with_queries=tuple(with_queries))
        return t.SetOperation(body.op, body.all, body.left, body.right,
                              body.order_by, body.limit, tuple(with_queries))

    def query_expr(self) -> t.Node:
        """query_term (UNION|EXCEPT [ALL] query_term)* [ORDER BY] [LIMIT];
        INTERSECT binds tighter than UNION/EXCEPT (SqlBase.g4 precedence)."""
        node = self.query_term()
        while self.at_kw("union", "except"):
            op = self.next().text
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            node = t.SetOperation(op, all_, node, self.query_term())
        order_by, limit = self._order_limit()
        if order_by or limit is not None:
            if isinstance(node, t.SetOperation):
                node = t.SetOperation(node.op, node.all, node.left,
                                      node.right, order_by, limit)
            else:
                node = dataclasses.replace(node, order_by=order_by,
                                           limit=limit)
        return node

    def query_term(self) -> t.Node:
        node = self.query_primary()
        while self.at_kw("intersect"):
            self.next()
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            node = t.SetOperation("intersect", all_, node,
                                  self.query_primary())
        return node

    def query_primary(self) -> t.Node:
        if self.at_op("(") and self.peek(1).kind == "KEYWORD" \
                and self.peek(1).text in ("select", "with", "("):
            self.next()
            q = self.query()
            self.expect_op(")")
            return q
        return self.query_body()

    def _order_limit(self):
        order_by: List[t.SortItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self.sort_item())
            while self.accept_op(","):
                order_by.append(self.sort_item())
        limit = None
        if self.accept_kw("limit"):
            tok = self.next()
            if tok.kind != "NUMBER":
                raise SqlSyntaxError("expected LIMIT count", tok.line,
                                     tok.col)
            limit = int(tok.text)
        return tuple(order_by), limit

    def query_body(self) -> t.Query:
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        self.accept_kw("all")
        select = [self.select_item()]
        while self.accept_op(","):
            select.append(self.select_item())

        relations: List[t.Relation] = []
        if self.accept_kw("from"):
            relations.append(self.relation())
            while self.accept_op(","):
                relations.append(self.relation())

        where = self.expression() if self.accept_kw("where") else None

        group_by: List[t.Expression] = []
        grouping_sets = None
        if self.accept_kw("group"):
            self.expect_kw("by")
            if self.at_kw("grouping", "rollup", "cube"):
                group_by, grouping_sets = self.grouping_element()
            else:
                group_by.append(self.expression())
                while self.accept_op(","):
                    group_by.append(self.expression())

        having = self.expression() if self.accept_kw("having") else None
        # ORDER BY / LIMIT are parsed by query_expr so they attach to the
        # whole set operation when UNION/INTERSECT/EXCEPT follows.
        return t.Query(tuple(select), tuple(relations), where,
                       tuple(group_by), having, (), None, distinct,
                       grouping_sets=grouping_sets)

    def grouping_element(self):
        """ROLLUP(a, b) / CUBE(a, b) / GROUPING SETS ((a,b),(a),()) ->
        (column list, index subsets)."""
        columns: List[t.Expression] = []

        def col_index(e: t.Expression) -> int:
            for i, c in enumerate(columns):
                if c == e:
                    return i
            columns.append(e)
            return len(columns) - 1

        if self.accept_kw("rollup"):
            self.expect_op("(")
            exprs = [self.expression()]
            while self.accept_op(","):
                exprs.append(self.expression())
            self.expect_op(")")
            idxs = [col_index(e) for e in exprs]
            sets = [tuple(idxs[:k]) for k in range(len(idxs), -1, -1)]
            return columns, tuple(sets)
        if self.accept_kw("cube"):
            self.expect_op("(")
            exprs = [self.expression()]
            while self.accept_op(","):
                exprs.append(self.expression())
            self.expect_op(")")
            idxs = [col_index(e) for e in exprs]
            sets = []
            for mask in range(1 << len(idxs), -1, -1):
                if mask < (1 << len(idxs)):
                    sets.append(tuple(i for b, i in enumerate(idxs)
                                      if mask & (1 << b)))
            return columns, tuple(sets)
        self.expect_kw("grouping")
        self.expect_kw("sets")
        self.expect_op("(")
        sets = []
        while True:
            if self.accept_op("("):
                subset = []
                if not self.at_op(")"):
                    subset.append(col_index(self.expression()))
                    while self.accept_op(","):
                        subset.append(col_index(self.expression()))
                self.expect_op(")")
                sets.append(tuple(subset))
            else:
                sets.append((col_index(self.expression()),))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return columns, tuple(sets)

    def select_item(self) -> t.SelectItem:
        if self.at_op("*"):
            self.next()
            return t.SelectItem(t.Star())
        # t.* form
        if (self.peek().kind in ("IDENT", "QIDENT")
                and self.peek(1).kind == "OP" and self.peek(1).text == "."
                and self.peek(2).kind == "OP" and self.peek(2).text == "*"):
            name = self.identifier()
            self.next()
            self.next()
            return t.SelectItem(t.Star((name,)))
        expr = self.expression()
        alias = None
        if self.accept_kw("as"):
            alias = self.identifier()
        elif self.peek().kind in ("IDENT", "QIDENT"):
            alias = self.identifier()
        return t.SelectItem(expr, alias)

    def sort_item(self) -> t.SortItem:
        expr = self.expression()
        ascending = True
        if self.accept_kw("asc"):
            ascending = True
        elif self.accept_kw("desc"):
            ascending = False
        nulls_first = None
        if self.accept_kw("nulls"):
            if self.accept_kw("first"):
                nulls_first = True
            else:
                self.expect_kw("last")
                nulls_first = False
        return t.SortItem(expr, ascending, nulls_first)

    # --- relations ---------------------------------------------------------
    def relation(self) -> t.Relation:
        rel = self.relation_primary()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.relation_primary()
                rel = t.Join("cross", rel, right)
                continue
            kind = None
            if self.at_kw("join"):
                kind = "inner"
            elif self.at_kw("inner"):
                self.next()
                kind = "inner"
            elif self.at_kw("left", "right", "full"):
                kind = self.next().text
                self.accept_kw("outer")
            if kind is None:
                return rel
            self.expect_kw("join")
            right = self.relation_primary()
            self.expect_kw("on")
            on = self.expression()
            rel = t.Join(kind, rel, right, on)

    def inline_values(self) -> t.InlineValues:
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            row = [self.expression()]
            while self.accept_op(","):
                row.append(self.expression())
            self.expect_op(")")
            rows.append(tuple(row))
            if not self.accept_op(","):
                break
        return t.InlineValues(tuple(rows))

    def relation_primary(self) -> t.Relation:
        if self.at_kw("unnest"):
            self.next()
            self.expect_op("(")
            args = [self.expression()]
            while self.accept_op(","):
                args.append(self.expression())
            self.expect_op(")")
            ordinality = False
            if self.accept_kw("with"):
                self.expect_kw("ordinality")
                ordinality = True
            alias, col_aliases = self._relation_alias()
            return t.Unnest(tuple(args), ordinality, alias, col_aliases)
        if self.at_kw("values"):
            iv = self.inline_values()
            alias, col_aliases = self._relation_alias()
            return t.InlineValues(iv.rows, alias, col_aliases)
        if self.accept_op("("):
            if self.at_kw("values"):
                iv = self.inline_values()
                self.expect_op(")")
                alias, col_aliases = self._relation_alias()
                return t.InlineValues(iv.rows, alias, col_aliases)
            if self.at_kw("select", "with"):
                q = self.query()
                self.expect_op(")")
                alias, col_aliases = self._relation_alias()
                return t.SubqueryRelation(q, alias, col_aliases)
            if self.at_op("("):
                # ambiguous '((': a parenthesized QUERY whose first
                # set-operation operand is itself parenthesized
                # ("((SELECT..) INTERSECT SELECT..) t", SqlBase.g4
                # queryPrimary), or a parenthesized RELATION (join
                # grouping) — try the query reading first, backtrack on
                # failure
                save = self.pos
                try:
                    q = self.query()
                    self.expect_op(")")
                    alias, col_aliases = self._relation_alias()
                    return t.SubqueryRelation(q, alias, col_aliases)
                except SqlSyntaxError:
                    self.pos = save
            rel = self.relation()
            self.expect_op(")")
            return rel
        name = self.qualified_name()
        alias, _ = self._relation_alias()
        return t.Table(name, alias)

    def _relation_alias(self):
        alias = None
        col_aliases: Tuple[str, ...] = ()
        if self.accept_kw("as"):
            alias = self.identifier()
        elif self.peek().kind in ("IDENT", "QIDENT"):
            alias = self.identifier()
        if alias is not None and self.at_op("("):
            self.next()
            cols = [self.identifier()]
            while self.accept_op(","):
                cols.append(self.identifier())
            self.expect_op(")")
            col_aliases = tuple(cols)
        return alias, col_aliases

    # --- expressions (precedence climbing) ---------------------------------
    def expression(self) -> t.Expression:
        return self.or_expr()

    def or_expr(self) -> t.Expression:
        left = self.and_expr()
        while self.accept_kw("or"):
            left = t.LogicalBinary("or", left, self.and_expr())
        return left

    def and_expr(self) -> t.Expression:
        left = self.not_expr()
        while self.accept_kw("and"):
            left = t.LogicalBinary("and", left, self.not_expr())
        return left

    def not_expr(self) -> t.Expression:
        if self.accept_kw("not"):
            return t.Not(self.not_expr())
        return self.predicate()

    def predicate(self) -> t.Expression:
        left = self.additive()
        while True:
            if self.at_op("=", "<", "<=", ">", ">=", "<>", "!="):
                op = self.next().text
                if op == "!=":
                    op = "<>"
                right = self.additive()
                left = t.Comparison(op, left, right)
                continue
            negated = False
            save = self.pos
            if self.accept_kw("not"):
                negated = True
            if self.accept_kw("between"):
                low = self.additive()
                self.expect_kw("and")
                high = self.additive()
                left = t.Between(left, low, high, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.query()
                    self.expect_op(")")
                    left = t.InSubquery(left, q, negated)
                else:
                    items = [self.expression()]
                    while self.accept_op(","):
                        items.append(self.expression())
                    self.expect_op(")")
                    left = t.InList(left, tuple(items), negated)
                continue
            if self.accept_kw("like"):
                pattern = self.additive()
                escape = None
                if self.accept_kw("escape"):
                    escape = self.additive()
                left = t.Like(left, pattern, escape, negated)
                continue
            if negated:
                self.pos = save  # bare NOT belongs to not_expr
                return left
            if self.accept_kw("is"):
                neg = bool(self.accept_kw("not"))
                self.expect_kw("null")
                left = t.IsNull(left, neg)
                continue
            return left

    def additive(self) -> t.Expression:
        left = self.multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().text
                left = t.ArithmeticBinary(op, left, self.multiplicative())
            elif self.at_op("||"):
                self.next()
                left = t.FunctionCall("concat",
                                      (left, self.multiplicative()))
            else:
                return left

    def multiplicative(self) -> t.Expression:
        left = self.unary()
        while self.at_op("*", "/", "%"):
            op = self.next().text
            left = t.ArithmeticBinary(op, left, self.unary())
        return left

    def unary(self) -> t.Expression:
        if self.at_op("-"):
            self.next()
            return t.ArithmeticUnary("-", self.unary())
        if self.at_op("+"):
            self.next()
            return self.unary()
        return self.primary()

    def table_properties(self) -> Tuple[Tuple[str, object], ...]:
        """WITH (k = literal, ...) — literals: string, number, boolean,
        ARRAY['a', ...] (the table-properties grammar subset the
        connectors consume)."""
        self.expect_kw("with")
        self.expect_op("(")
        props = []
        while True:
            key = self.identifier()
            self.expect_op("=")
            props.append((key, self._property_value()))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return tuple(props)

    def _property_value(self):
        tok = self.peek()
        if tok.kind == "STRING":
            return self.next().text
        if tok.kind == "NUMBER":
            text = self.next().text
            return float(text) if "." in text or "e" in text else int(text)
        if self.accept_kw("true"):
            return True
        if self.accept_kw("false"):
            return False
        if self.accept_kw("array"):
            self.expect_op("[")
            items = []
            if not self.at_op("]"):
                items.append(self._property_value())
                while self.accept_op(","):
                    items.append(self._property_value())
            self.expect_op("]")
            return items
        raise SqlSyntaxError("expected property value", tok.line, tok.col)

    def privilege(self) -> str:
        tok = self.next()
        word = tok.text
        if word not in ("select", "insert", "delete", "all"):
            raise SqlSyntaxError(f"unknown privilege {word!r}",
                                 tok.line, tok.col)
        if word == "all":
            self.accept_word("privileges")
        return word

    def _opt_like(self) -> Optional[str]:
        if self.accept_kw("like"):
            tok = self.next()
            if tok.kind != "STRING":
                raise SqlSyntaxError("expected string after LIKE",
                                     tok.line, tok.col)
            return tok.text
        return None

    def _text_between(self, start_pos: int, end_pos: int) -> str:
        """Original SQL text between two token positions (used to store a
        view's defining query verbatim)."""
        if not self.sql:
            return ""
        line_off = [0]
        for ln in self.sql.splitlines(keepends=True):
            line_off.append(line_off[-1] + len(ln))

        def offset(tok: Token) -> int:
            if tok.kind == "EOF":
                return len(self.sql)
            return line_off[tok.line - 1] + tok.col - 1

        lo = offset(self.toks[start_pos])
        hi = (offset(self.toks[end_pos])
              if end_pos < len(self.toks) else len(self.sql))
        return self.sql[lo:hi].strip().rstrip(";").strip()

    def primary(self) -> t.Expression:
        e = self._primary_base()
        while True:
            if self.at_op("["):
                self.next()
                idx = self.expression()
                self.expect_op("]")
                e = t.Subscript(e, idx)
                continue
            # field deref on a computed base (identifiers consume dots in
            # qualified_name; row fields there resolve during analysis)
            if (self.at_op(".") and not isinstance(e, t.Identifier)
                    and self.peek(1).kind in ("IDENT", "QIDENT")):
                self.next()
                e = t.Deref(e, self.identifier())
                continue
            return e

    def _primary_base(self) -> t.Expression:
        tok = self.peek()
        if tok.kind == "OP" and tok.text == "?":
            self.next()
            p = t.Parameter(self._param_seq)
            self._param_seq += 1
            return p
        if tok.kind == "NUMBER":
            self.next()
            return t.NumberLiteral(tok.text)
        if tok.kind == "STRING":
            self.next()
            return t.StringLiteral(tok.text)
        if tok.kind == "OP" and tok.text == "(":
            self.next()
            if self.at_kw("select", "with"):
                q = self.query()
                self.expect_op(")")
                return t.ScalarSubquery(q)
            e = self.expression()
            self.expect_op(")")
            return e
        if tok.kind == "KEYWORD":
            return self._keyword_primary(tok)
        if tok.kind in ("IDENT", "QIDENT"):
            # function call?
            if (self.peek(1).kind == "OP" and self.peek(1).text == "("):
                if tok.text == "try_cast":
                    self.next()
                    self.expect_op("(")
                    e = self.expression()
                    self.expect_kw("as")
                    type_name = self.type_name()
                    self.expect_op(")")
                    return t.TryCast(e, type_name)
                if tok.text == "position":
                    self.next()
                    self.expect_op("(")
                    needle = self.additive()   # below the IN predicate
                    self.expect_kw("in")
                    hay = self.expression()
                    self.expect_op(")")
                    return t.FunctionCall("strpos", (hay, needle))
                return self.function_call(self.identifier())
            if (tok.kind == "IDENT" and tok.text == "decimal"
                    and self.peek(1).kind == "STRING"):
                # DECIMAL '1.2' typed literal (SqlBase.g4 numericLiteral)
                self.next()
                return t.TypedLiteral("decimal", self.next().text)
            return t.Identifier(self.qualified_name())
        raise SqlSyntaxError(f"unexpected {tok.text or 'end of input'!r}",
                             tok.line, tok.col)

    def _keyword_primary(self, tok: Token) -> t.Expression:
        word = tok.text
        if word == "null":
            self.next()
            return t.NullLiteral()
        if word == "array":
            self.next()
            self.expect_op("[")
            items: List[t.Expression] = []
            if not self.at_op("]"):
                items.append(self.expression())
                while self.accept_op(","):
                    items.append(self.expression())
            self.expect_op("]")
            return t.ArrayConstructor(tuple(items))
        if word == "row" and self.peek(1).kind == "OP" \
                and self.peek(1).text == "(":
            self.next()
            return self.function_call("row")
        if word in ("true", "false"):
            self.next()
            return t.BooleanLiteral(word == "true")
        if word in ("date", "timestamp", "time", "decimal"):
            if self.peek(1).kind == "STRING":
                self.next()
                return t.TypedLiteral(word, self.next().text)
            self.next()
            return t.Identifier((word,))
        if word == "interval":
            self.next()
            sign = 1
            if self.accept_op("-"):
                sign = -1
            val = self.next()
            if val.kind != "STRING":
                raise SqlSyntaxError("expected interval string", val.line,
                                     val.col)
            unit_tok = self.next()
            unit = unit_tok.text
            if unit not in ("year", "month", "day", "hour", "minute",
                            "second"):
                raise SqlSyntaxError(f"bad interval unit {unit!r}",
                                     unit_tok.line, unit_tok.col)
            return t.IntervalLiteral(val.text, unit, sign)
        if word == "grouping" and self.peek(1).kind == "OP" \
                and self.peek(1).text == "(":
            # grouping(col, ...) function (vs GROUPING SETS keyword)
            self.next()
            return self.function_call("grouping")
        if word == "case":
            self.next()
            operand = None
            if not self.at_kw("when"):
                operand = self.expression()
            whens = []
            while self.accept_kw("when"):
                cond = self.expression()
                self.expect_kw("then")
                whens.append((cond, self.expression()))
            default = self.expression() if self.accept_kw("else") else None
            self.expect_kw("end")
            return t.Case(operand, tuple(whens), default)
        if word == "cast":
            self.next()
            self.expect_op("(")
            e = self.expression()
            self.expect_kw("as")
            type_name = self.type_name()
            self.expect_op(")")
            return t.Cast(e, type_name)
        if word == "extract":
            self.next()
            self.expect_op("(")
            field = self.next().text
            self.expect_kw("from")
            e = self.expression()
            self.expect_op(")")
            return t.Extract(field, e)
        if word == "coalesce":
            self.next()
            self.expect_op("(")
            args = [self.expression()]
            while self.accept_op(","):
                args.append(self.expression())
            self.expect_op(")")
            return t.Coalesce(tuple(args))
        if word == "nullif":
            self.next()
            self.expect_op("(")
            first = self.expression()
            self.expect_op(",")
            second = self.expression()
            self.expect_op(")")
            return t.NullIf(first, second)
        if word == "substring":
            self.next()
            self.expect_op("(")
            e = self.expression()
            if self.accept_kw("from"):
                start = self.expression()
                length = self.expression() if self.accept_kw("for") else None
            else:
                self.expect_op(",")
                start = self.expression()
                length = None
                if self.accept_op(","):
                    length = self.expression()
            self.expect_op(")")
            args = (e, start) if length is None else (e, start, length)
            return t.FunctionCall("substr", args)
        if word == "exists":
            self.next()
            self.expect_op("(")
            q = self.query()
            self.expect_op(")")
            return t.Exists(q)
        if word in ("year", "month", "day", "hour", "minute", "second",
                    "first", "last"):
            if self.peek(1).kind == "OP" and self.peek(1).text == "(":
                self.next()
                return self.function_call(word)
            self.next()
            return t.Identifier((word,))
        raise SqlSyntaxError(f"unexpected keyword {word!r}", tok.line,
                             tok.col)

    def function_call(self, name: str) -> t.Expression:
        self.expect_op("(")
        if self.accept_op("*"):
            self.expect_op(")")
            call = t.FunctionCall(name, (), is_star=True)
        elif self.at_op(")"):
            self.next()
            call = t.FunctionCall(name, ())
        else:
            distinct = bool(self.accept_kw("distinct"))
            self.accept_kw("all")
            args = [self._call_arg()]
            while self.accept_op(","):
                args.append(self._call_arg())
            self.expect_op(")")
            call = t.FunctionCall(name, tuple(args), distinct)
        if self.accept_kw("over"):
            call = dataclasses.replace(call, window=self.window_spec())
        return call

    def _call_arg(self) -> t.Expression:
        """A function argument: lambda (``x -> e`` / ``(x, y) -> e``) or
        a plain expression."""
        if (self.peek().kind in ("IDENT", "QIDENT")
                and self.peek(1).kind == "OP" and self.peek(1).text == "->"):
            param = self.identifier()
            self.next()  # ->
            return t.Lambda((param,), self.expression())
        if self.at_op("("):
            # lookahead: "(" ident ("," ident)* ")" "->"
            i = 1
            params = []
            while self.peek(i).kind in ("IDENT", "QIDENT"):
                params.append(self.peek(i).text)
                if self.peek(i + 1).kind == "OP" \
                        and self.peek(i + 1).text == ",":
                    i += 2
                    continue
                break
            if (params and self.peek(i + 1).kind == "OP"
                    and self.peek(i + 1).text == ")"
                    and self.peek(i + 2).kind == "OP"
                    and self.peek(i + 2).text == "->"):
                self.next()  # (
                names = [self.identifier()]
                while self.accept_op(","):
                    names.append(self.identifier())
                self.expect_op(")")
                self.expect_op("->")
                return t.Lambda(tuple(names), self.expression())
        return self.expression()

    def window_spec(self) -> t.WindowSpec:
        self.expect_op("(")
        partition_by: List[t.Expression] = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition_by.append(self.expression())
            while self.accept_op(","):
                partition_by.append(self.expression())
        order_by: List[t.SortItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self.sort_item())
            while self.accept_op(","):
                order_by.append(self.sort_item())
        frame = None
        if self.at_kw("rows", "range"):
            unit = self.next().text
            if self.accept_kw("between"):
                start = self.frame_bound()
                self.expect_kw("and")
                end = self.frame_bound()
            else:
                start = self.frame_bound()
                end = t.FrameBound("current")
            frame = t.WindowFrame(unit, start, end)
        self.expect_op(")")
        return t.WindowSpec(tuple(partition_by), tuple(order_by), frame)

    def frame_bound(self) -> t.FrameBound:
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                return t.FrameBound("unbounded_preceding")
            self.expect_kw("following")
            return t.FrameBound("unbounded_following")
        if self.accept_kw("current"):
            self.expect_kw("row")
            return t.FrameBound("current")
        value = self.expression()
        if self.accept_kw("preceding"):
            return t.FrameBound("preceding", value)
        self.expect_kw("following")
        return t.FrameBound("following", value)

    def type_name(self) -> str:
        tok = self.next()
        if tok.kind not in ("IDENT", "KEYWORD"):
            raise SqlSyntaxError("expected type name", tok.line, tok.col)
        name = tok.text
        if name == "double" and self.peek().text == "precision":
            self.next()
        if name in ("array", "map", "row") and self.at_op("("):
            self.next()
            parts = []
            while not self.at_op(")"):
                if name == "row":
                    fname = self.identifier()
                    parts.append(f"{fname} {self.type_name()}")
                else:
                    parts.append(self.type_name())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return f"{name}({','.join(parts)})"
        if self.at_op("("):
            self.next()
            params = [self.next().text]
            while self.accept_op(","):
                params.append(self.next().text)
            self.expect_op(")")
            name = f"{name}({','.join(params)})"
        return name
