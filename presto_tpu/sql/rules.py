"""Iterative rule-based optimizer.

The reference drives 113 pattern rules to fixpoint over a Memo of plan
groups (presto-main/.../sql/planner/iterative/IterativeOptimizer.java,
Memo.java, iterative/rule/).  This module plays that role for the
immutable-dataclass plan tree: each Rule pattern-matches one node and
returns a replacement (or None), and ``iterative_optimize`` applies the
rule set bottom-up to fixpoint with an explicit rewrite budget (the
IterativeOptimizer timeout analogue).  This destructive fixpointing
serves the always-good rules below (each fires only when it improves
the plan); decisions that need to HOLD alternatives — join order,
exchange placement — run the same Rule protocol non-destructively over
Memo groups in sql/memo.py, where cost extraction picks the winner.

Rules implemented (reference analogues cited per class):
- MergeFilters, MergeLimits
- PushLimitThroughProject / PushLimitThroughUnion
- PushPartialAggregationThroughUnion (partial->final split, the
  PushPartialAggregationThroughExchange idea applied at the logical
  tier; the fragmenter re-uses the same partial/final contract across
  remote exchanges)
- PushProjectionThroughJoin (computed single-side expressions evaluate
  below the join on preserved sides)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.expr.ir import InputRef, RowExpression, input_channels
from presto_tpu.sql.plan import (
    AggregationNode, FilterNode, JoinNode, LimitNode, PlanNode,
    ProjectNode, UnionNode,
)


class RuleContext:
    def __init__(self, metadata=None, config=None):
        self.metadata = metadata
        self.config = config


class Rule:
    name = "rule"

    def apply(self, node: PlanNode,
              ctx: RuleContext) -> Optional[PlanNode]:
        raise NotImplementedError


class MergeFilters(Rule):
    """Filter(Filter(x)) -> Filter(x) with ANDed predicates
    (MergeFilters.java role)."""

    name = "merge_filters"

    def apply(self, node, ctx):
        if isinstance(node, FilterNode) \
                and isinstance(node.source, FilterNode):
            from presto_tpu.expr import build as B

            return FilterNode(node.source.source,
                              B.and_(node.source.predicate,
                                     node.predicate))
        return None


class MergeLimits(Rule):
    """Limit(n, Limit(m, x)) -> Limit(min(n, m), x)
    (MergeLimits.java role)."""

    name = "merge_limits"

    def apply(self, node, ctx):
        if isinstance(node, LimitNode) \
                and isinstance(node.source, LimitNode):
            return LimitNode(node.source.source,
                             min(node.count, node.source.count))
        return None


class PushLimitThroughProject(Rule):
    """Limit(Project(x)) -> Project(Limit(x))
    (PushLimitThroughProject.java role): lets limits reach sorts/scans
    and shrinks the rows the projection evaluates."""

    name = "push_limit_through_project"

    def apply(self, node, ctx):
        if isinstance(node, LimitNode) \
                and isinstance(node.source, ProjectNode):
            p = node.source
            return ProjectNode(LimitNode(p.source, node.count),
                               p.expressions, p.columns)
        return None


class PushLimitThroughUnion(Rule):
    """Limit(Union(b...)) -> Limit(Union(Limit(b)...))
    (PushLimitThroughUnion.java role): each branch produces at most n
    rows before the concatenation."""

    name = "push_limit_through_union"

    def apply(self, node, ctx):
        if not (isinstance(node, LimitNode)
                and isinstance(node.source, UnionNode)):
            return None
        u = node.source
        if all(isinstance(b, LimitNode) and b.count <= node.count
               for b in u.inputs):
            return None  # already pushed (fixpoint guard)
        branches = tuple(
            b if isinstance(b, LimitNode) and b.count <= node.count
            else LimitNode(b, node.count)
            for b in u.inputs)
        return LimitNode(UnionNode(branches, u.columns), node.count)


class PushProjectionThroughUnion(Rule):
    """Project(Union(b...)) -> Union(Project(b)...)
    (PushProjectionThroughUnion.java role): normalizes plans so
    union-aware rules (partial aggregation, limits) see the union
    directly, and evaluates projections in the branch pipelines."""

    name = "push_projection_through_union"

    def apply(self, node, ctx):
        if not (isinstance(node, ProjectNode)
                and isinstance(node.source, UnionNode)):
            return None
        u = node.source
        branches = tuple(
            ProjectNode(b, node.expressions, node.columns)
            for b in u.inputs)
        return UnionNode(branches, node.columns)


class PushPartialAggregationThroughUnion(Rule):
    """Aggregate(single, Union) -> Aggregate(final, Union(Aggregate(
    partial, branch)...)).

    The PushPartialAggregationThroughExchange idea
    (presto-main/.../iterative/rule/
    PushPartialAggregationThroughExchange.java) applied where the
    logical plan itself concatenates streams: each UNION ALL branch
    pre-aggregates into the spec's component columns and the final step
    merges them, so the union moves group-sized — not row-sized — data.
    The fragmenter's partial/final split across remote exchanges uses
    the identical component-column contract (server/fragmenter.py)."""

    name = "push_partial_agg_through_union"

    def apply(self, node, ctx):
        if not (isinstance(node, AggregationNode)
                and node.step == "single"
                and isinstance(node.source, UnionNode)
                and node.aggregates
                and not any(a.distinct for a in node.aggregates)):
            return None
        u = node.source
        ngroups = len(node.group_channels)
        comp_cols: List[Tuple[str, T.Type]] = [
            node.columns[i] for i in range(ngroups)]
        ci = 0
        for agg in node.aggregates:
            for _prim, ctype in agg.spec.components:
                comp_cols.append((f"$comp{ci}", ctype))
                ci += 1
        partials = tuple(
            AggregationNode(b, node.group_channels, node.aggregates,
                            tuple(comp_cols), step="partial")
            for b in u.inputs)
        union = UnionNode(partials, tuple(comp_cols))
        return AggregationNode(union, tuple(range(ngroups)),
                               node.aggregates, node.columns,
                               step="final")


class PushProjectionThroughJoin(Rule):
    """Project(Join): computed expressions that reference only one
    PRESERVED side evaluate below the join
    (PushProjectionThroughJoin.java role).  Inner/cross joins preserve
    both sides; LEFT preserves the left only (a computed right-side
    column must null-extend, which computing below would break)."""

    name = "push_projection_through_join"

    def apply(self, node, ctx):
        if not (isinstance(node, ProjectNode)
                and isinstance(node.source, JoinNode)):
            return None
        join = node.source
        sides_ok = {"inner": (True, True), "cross": (True, True),
                    "left": (True, False)}.get(join.kind)
        if sides_ok is None:
            return None
        nleft = len(join.left.columns)
        push_left: List[int] = []
        push_right: List[int] = []
        for i, e in enumerate(node.expressions):
            if isinstance(e, InputRef):
                continue
            chans = input_channels(e)
            if not chans:
                continue
            if sides_ok[0] and all(ch < nleft for ch in chans):
                push_left.append(i)
            elif sides_ok[1] and all(ch >= nleft for ch in chans):
                push_right.append(i)
        if not push_left and not push_right:
            return None

        from presto_tpu.sql.optimizer import remap

        def extend(child, indices, offset):
            exprs = [InputRef(j, t)
                     for j, (_n, t) in enumerate(child.columns)]
            cols = list(child.columns)
            pos = {}
            for i in indices:
                e = remap(node.expressions[i],
                          {ch: ch - offset
                           for ch in input_channels(node.expressions[i])})
                pos[i] = len(exprs)
                exprs.append(e)
                cols.append((f"$push{i}", e.type))
            return (ProjectNode(child, tuple(exprs), tuple(cols)), pos)

        new_left, lpos = extend(join.left, push_left, 0)
        new_right, rpos = extend(join.right, push_right, nleft)
        nleft_new = len(new_left.columns)
        # old join output channel -> new join output channel
        shift = {ch: ch for ch in range(nleft)}
        for ch in range(nleft, len(join.columns)):
            shift[ch] = ch - nleft + nleft_new
        cols = tuple(new_left.columns) + tuple(new_right.columns)
        residual = (remap(join.residual, shift)
                    if join.residual is not None else None)
        new_join = dataclasses.replace(
            join, left=new_left, right=new_right, columns=cols,
            right_keys=join.right_keys, residual=residual)
        out_exprs: List[RowExpression] = []
        for i, e in enumerate(node.expressions):
            if i in lpos:
                out_exprs.append(InputRef(lpos[i], e.type))
            elif i in rpos:
                out_exprs.append(InputRef(nleft_new + rpos[i], e.type))
            else:
                out_exprs.append(remap(e, {ch: shift[ch]
                                           for ch in input_channels(e)}))
        return ProjectNode(new_join, tuple(out_exprs), node.columns)


DEFAULT_RULES: Sequence[Rule] = (
    MergeFilters(), MergeLimits(), PushLimitThroughProject(),
    PushLimitThroughUnion(), PushProjectionThroughUnion(),
    PushPartialAggregationThroughUnion(), PushProjectionThroughJoin(),
)


def _children(node: PlanNode) -> List[PlanNode]:
    return list(node.sources)


def iterative_optimize(node: PlanNode, rules: Sequence[Rule],
                       ctx: RuleContext,
                       budget: int = 10_000) -> PlanNode:
    """Bottom-up rewrite to fixpoint.  Each position retries the whole
    rule list until none fires (then its subtree is stable, because
    rules only ever return strictly-rewritten nodes); the global budget
    bounds pathological rule interactions the way the reference's
    optimizer timeout does."""
    from presto_tpu.sql.optimizer import _replace_sources

    fired = [0]

    def rewrite(n: PlanNode) -> PlanNode:
        n = _replace_sources(n, [rewrite(s) for s in n.sources])
        progress = True
        while progress and fired[0] < budget:
            progress = False
            for rule in rules:
                out = rule.apply(n, ctx)
                if out is not None:
                    fired[0] += 1
                    # a rule may expose new matches below its result
                    n = _replace_sources(
                        out, [rewrite(s) for s in out.sources])
                    progress = True
                    break
        return n

    return rewrite(node)
