"""SQL AST.

Compact dataclass analogue of the reference's ~170 node classes under
presto-parser/src/main/java/io/prestosql/sql/tree/ — one node kind per
grammar production the engine supports.  Positions are (line, col) for
error messages.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

D = dataclasses.dataclass


class Node:
    pass


class Expression(Node):
    pass


# --- literals / terms ------------------------------------------------------

@D(frozen=True)
class Identifier(Expression):
    parts: Tuple[str, ...]  # a.b.c; lowercased unless quoted

    def __str__(self):
        return ".".join(self.parts)


@D(frozen=True)
class NumberLiteral(Expression):
    text: str  # original text; analyzer decides integer/decimal/double


@D(frozen=True)
class StringLiteral(Expression):
    value: str


@D(frozen=True)
class BooleanLiteral(Expression):
    value: bool


@D(frozen=True)
class NullLiteral(Expression):
    pass


@D(frozen=True)
class TypedLiteral(Expression):
    """DATE 'x' / TIMESTAMP 'x' / DECIMAL 'x' / CHAR 'x'."""

    type_name: str
    value: str


@D(frozen=True)
class IntervalLiteral(Expression):
    value: str
    unit: str       # year|month|day|hour|minute|second
    sign: int = 1


@D(frozen=True)
class Star(Expression):
    qualifier: Optional[Tuple[str, ...]] = None  # t.* qualifier


@D(frozen=True)
class Parameter(Expression):
    index: int


# --- compound expressions --------------------------------------------------

@D(frozen=True)
class FrameBound(Node):
    """One end of a window frame: kind in {unbounded_preceding, preceding,
    current, following, unbounded_following}; value set for the bounded
    kinds."""

    kind: str
    value: Optional["Expression"] = None


@D(frozen=True)
class WindowFrame(Node):
    unit: str                        # rows | range
    start: FrameBound
    end: FrameBound


@D(frozen=True)
class WindowSpec(Node):
    """OVER (...) clause (Window in SqlBase.g4)."""

    partition_by: Tuple["Expression", ...] = ()
    order_by: Tuple["SortItem", ...] = ()
    frame: Optional[WindowFrame] = None


@D(frozen=True)
class FunctionCall(Expression):
    name: str
    args: Tuple[Expression, ...]
    distinct: bool = False           # count(DISTINCT x)
    is_star: bool = False            # count(*)
    window: Optional[WindowSpec] = None  # fn(...) OVER (...)


@D(frozen=True)
class Cast(Expression):
    expr: Expression
    type_name: str                   # e.g. "double", "decimal(12,2)"


@D(frozen=True)
class TryCast(Expression):
    """TRY_CAST(x AS t): NULL instead of an error on conversion failure."""

    expr: Expression
    type_name: str


@D(frozen=True)
class ArrayConstructor(Expression):
    items: Tuple[Expression, ...]


@D(frozen=True)
class Subscript(Expression):
    base: Expression
    index: Expression


@D(frozen=True)
class Lambda(Expression):
    params: Tuple[str, ...]
    body: Expression


@D(frozen=True)
class Deref(Expression):
    """Row-field access on a non-identifier base: ``expr.field``."""

    base: Expression
    field: str


@D(frozen=True)
class Extract(Expression):
    field: str                       # year|month|day|...
    expr: Expression


@D(frozen=True)
class ArithmeticBinary(Expression):
    op: str                          # + - * / %
    left: Expression
    right: Expression


@D(frozen=True)
class ArithmeticUnary(Expression):
    op: str                          # -
    expr: Expression


@D(frozen=True)
class Comparison(Expression):
    op: str                          # = != <> < <= > >=
    left: Expression
    right: Expression


@D(frozen=True)
class Between(Expression):
    expr: Expression
    low: Expression
    high: Expression
    negated: bool = False


@D(frozen=True)
class InList(Expression):
    expr: Expression
    items: Tuple[Expression, ...]
    negated: bool = False


@D(frozen=True)
class InSubquery(Expression):
    expr: Expression
    query: "Query"
    negated: bool = False


@D(frozen=True)
class Exists(Expression):
    query: "Query"
    negated: bool = False


@D(frozen=True)
class ScalarSubquery(Expression):
    query: "Query"


@D(frozen=True)
class Like(Expression):
    expr: Expression
    pattern: Expression
    escape: Optional[Expression] = None
    negated: bool = False


@D(frozen=True)
class IsNull(Expression):
    expr: Expression
    negated: bool = False


@D(frozen=True)
class Not(Expression):
    expr: Expression


@D(frozen=True)
class LogicalBinary(Expression):
    op: str                          # and|or
    left: Expression
    right: Expression


@D(frozen=True)
class Case(Expression):
    operand: Optional[Expression]    # CASE x WHEN ... vs CASE WHEN ...
    whens: Tuple[Tuple[Expression, Expression], ...]
    default: Optional[Expression]


@D(frozen=True)
class Coalesce(Expression):
    args: Tuple[Expression, ...]


@D(frozen=True)
class NullIf(Expression):
    first: Expression
    second: Expression


# --- relations -------------------------------------------------------------

class Relation(Node):
    pass


@D(frozen=True)
class Table(Relation):
    name: Tuple[str, ...]            # [catalog.][schema.]table
    alias: Optional[str] = None


@D(frozen=True)
class SubqueryRelation(Relation):
    query: "Query"
    alias: Optional[str] = None
    column_aliases: Tuple[str, ...] = ()


@D(frozen=True)
class Join(Relation):
    kind: str                        # inner|left|right|full|cross
    left: Relation
    right: Relation
    on: Optional[Expression] = None


@D(frozen=True)
class Unnest(Relation):
    """UNNEST(a1, a2, ...) [WITH ORDINALITY] [alias(col, ...)]."""

    args: Tuple[Expression, ...]
    ordinality: bool = False
    alias: Optional[str] = None
    column_aliases: Tuple[str, ...] = ()


# --- query -----------------------------------------------------------------

@D(frozen=True)
class SelectItem(Node):
    expr: Expression
    alias: Optional[str] = None


@D(frozen=True)
class SortItem(Node):
    expr: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None


@D(frozen=True)
class Query(Node):
    select: Tuple[SelectItem, ...]
    relations: Tuple[Relation, ...]  # FROM a, b, c (implicit cross joins)
    where: Optional[Expression] = None
    group_by: Tuple[Expression, ...] = ()
    having: Optional[Expression] = None
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    distinct: bool = False
    with_queries: Tuple[Tuple[str, "Query"], ...] = ()
    # GROUPING SETS / ROLLUP / CUBE: when set, ``group_by`` holds the
    # full (deduplicated) grouping column list and each entry here is the
    # subset of indices into it that one grouping set keeps
    grouping_sets: Optional[Tuple[Tuple[int, ...], ...]] = None


@D(frozen=True)
class SetOperation(Node):
    """UNION / INTERSECT / EXCEPT over two query bodies.  ORDER BY and
    LIMIT written after the last branch attach here (they apply to the
    whole operation)."""

    op: str                          # union | intersect | except
    all: bool                        # UNION ALL vs UNION [DISTINCT]
    left: Node                       # Query | SetOperation
    right: Node
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    with_queries: Tuple[Tuple[str, "Query"], ...] = ()


@D(frozen=True)
class InlineValues(Relation):
    """VALUES (e, ...), (e, ...) — in FROM position or as INSERT source."""

    rows: Tuple[Tuple["Expression", ...], ...]
    alias: Optional[str] = None
    column_aliases: Tuple[str, ...] = ()


@D(frozen=True)
class CreateTable(Node):
    table: Tuple[str, ...]
    columns: Tuple[Tuple[str, str], ...]   # (name, type string)
    if_not_exists: bool = False
    # WITH (k = v, ...) table properties (format, partitioned_by, ...)
    properties: Tuple[Tuple[str, Any], ...] = ()


@D(frozen=True)
class CreateTableAs(Node):
    table: Tuple[str, ...]
    query: Node
    if_not_exists: bool = False
    properties: Tuple[Tuple[str, Any], ...] = ()


@D(frozen=True)
class Insert(Node):
    table: Tuple[str, ...]
    columns: Tuple[str, ...]               # () = positional
    source: Node                           # Query | SetOperation | InlineValues


@D(frozen=True)
class DropTable(Node):
    table: Tuple[str, ...]
    if_exists: bool = False


@D(frozen=True)
class CallProcedure(Node):
    name: Tuple[str, ...]            # e.g. ('system', 'runtime', 'kill_query')
    args: Tuple["Expression", ...]


@D(frozen=True)
class Explain(Node):
    statement: Node
    analyze: bool = False
    # EXPLAIN (TYPE ...) — logical | distributed | validate | io
    plan_type: str = "logical"


@D(frozen=True)
class SetSession(Node):
    name: str
    value: str                       # literal text


@D(frozen=True)
class ResetSession(Node):
    name: str


@D(frozen=True)
class ShowSession(Node):
    pass


@D(frozen=True)
class ShowTables(Node):
    catalog: Optional[str] = None
    like: Optional[str] = None


@D(frozen=True)
class ShowColumns(Node):
    table: Tuple[str, ...]


# --- additional statements (SqlBase.g4 statement alternatives) -------------

@D(frozen=True)
class Delete(Node):
    """DELETE FROM t [WHERE e] (DeleteOperator/TableDeleteOperator role)."""

    table: Tuple[str, ...]
    where: Optional[Expression] = None


@D(frozen=True)
class Prepare(Node):
    """PREPARE name FROM <statement>; the statement may contain ?
    parameters (Parameter nodes).  ``original_sql`` keeps the statement
    text verbatim for the client protocol's added-prepare exchange."""

    name: str
    statement: Node
    original_sql: str = ""


@D(frozen=True)
class ExecutePrepared(Node):
    """EXECUTE name [USING expr, ...]."""

    name: str
    parameters: Tuple[Expression, ...] = ()


@D(frozen=True)
class Deallocate(Node):
    name: str


@D(frozen=True)
class DescribeInput(Node):
    name: str


@D(frozen=True)
class DescribeOutput(Node):
    name: str


@D(frozen=True)
class ShowCatalogs(Node):
    like: Optional[str] = None


@D(frozen=True)
class ShowSchemas(Node):
    catalog: Optional[str] = None
    like: Optional[str] = None


@D(frozen=True)
class ShowFunctions(Node):
    like: Optional[str] = None


@D(frozen=True)
class ShowStats(Node):
    """SHOW STATS FOR table (SHOW STATS FOR (query) unsupported)."""

    table: Tuple[str, ...]


@D(frozen=True)
class ShowCreateTable(Node):
    table: Tuple[str, ...]


@D(frozen=True)
class ShowCreateView(Node):
    view: Tuple[str, ...]


@D(frozen=True)
class Use(Node):
    """USE catalog | USE catalog.schema."""

    catalog: str
    schema: Optional[str] = None


@D(frozen=True)
class StartTransaction(Node):
    pass


@D(frozen=True)
class Commit(Node):
    pass


@D(frozen=True)
class Rollback(Node):
    pass


@D(frozen=True)
class CreateView(Node):
    view: Tuple[str, ...]
    query: Node
    replace: bool = False
    original_sql: str = ""            # stored/rendered by SHOW CREATE VIEW


@D(frozen=True)
class DropView(Node):
    view: Tuple[str, ...]
    if_exists: bool = False


@D(frozen=True)
class Analyze(Node):
    """ANALYZE t — collect table/column statistics."""

    table: Tuple[str, ...]


@D(frozen=True)
class Grant(Node):
    privileges: Tuple[str, ...]       # ('select','insert',...) or ('all',)
    table: Tuple[str, ...]
    grantee: str


@D(frozen=True)
class Revoke(Node):
    privileges: Tuple[str, ...]
    table: Tuple[str, ...]
    grantee: str


@D(frozen=True)
class RenameTable(Node):
    """ALTER TABLE t RENAME TO u."""

    table: Tuple[str, ...]
    new_name: Tuple[str, ...]


# --- tree utilities --------------------------------------------------------

def _rewrite(node, fn):
    """Bottom-up structural rewrite over the dataclass tree: applies ``fn``
    to every Node after rewriting its children; tuples are rewritten
    element-wise.  Non-Node leaves pass through."""
    if isinstance(node, tuple):
        return tuple(_rewrite(x, fn) for x in node)
    if not isinstance(node, Node):
        return node
    changes = {}
    for f in dataclasses.fields(node):
        old = getattr(node, f.name)
        new = _rewrite(old, fn)
        if new is not old and new != old:
            changes[f.name] = new
    if changes:
        node = dataclasses.replace(node, **changes)
    return fn(node)


def parameter_count(stmt: Node) -> int:
    """Number of ? parameters in the statement (max index + 1)."""
    count = [0]

    def visit(n):
        if isinstance(n, Parameter):
            count[0] = max(count[0], n.index + 1)
        return n

    _rewrite(stmt, visit)
    return count[0]


def substitute_parameters(stmt: Node,
                          values: Tuple[Expression, ...]) -> Node:
    """Replace each Parameter node with the corresponding expression
    (EXECUTE ... USING binding, QueryPreparer.java role)."""
    need = parameter_count(stmt)
    if need != len(values):
        raise ValueError(
            f"statement has {need} parameters, {len(values)} values given")

    def visit(n):
        if isinstance(n, Parameter):
            return values[n.index]
        return n

    return _rewrite(stmt, visit)
