"""Memo-based cost exploration: Cascades-style groups + cost pruning.

The reference holds plan alternatives in a Memo of groups with GroupReference
leaves (presto-main/.../sql/planner/iterative/Memo.java,
GroupReference.java), drives rules over them via IterativeOptimizer.java,
and commits to the cheapest alternative through CostComparator.java over
the stats-derived CostCalculator estimates.  sql/rules.py rewrites
destructively to fixpoint, which is fine for always-good rules but cannot
hold alternatives — so join order and exchange placement stayed greedy
heuristics in optimizer.extract_joins.  This module adds the missing tier:

- ``Memo`` / ``GroupRef``: groups of logically-equivalent members whose
  children are group references, deduplicated structurally (Memo.java's
  rewriteChildren + GroupReference sharing);
- ``MemoStatsCalculator``: the stats derivation (sql/stats.py) extended
  through group references — a group's logical properties come from its
  first (original) member;
- ``CostModel`` + ``CostComparator``: cumulative (cpu, memory, network)
  estimates — bytes processed, build-side residency, and per-distribution
  exchange traffic — weighted like the reference's CostComparator
  defaults (cost/CostCalculatorUsingExchanges.java, CostComparator.java);
- ``MemoOptimizer``: the exploration driver.  It runs ordinary
  ``rules.Rule`` instances NON-destructively over groups (each match adds
  an alternative member; the original stays), materializing depth-1
  bindings the way the reference's Matcher resolves GroupReferences
  through Lookup.resolve, and extracts the cheapest plan per group;
- the first two exploration rules that need alternatives:
  ``JoinEnumerator`` (the ReorderJoins.java role — bounded bushy
  enumeration over optimizer.JoinGraph, one memo group per relation
  subset) and ``DetermineJoinDistribution`` (the
  DetermineJoinDistributionType.java role — REPLICATED vs PARTITIONED by
  exchange cost instead of the fragmenter's row-count threshold).

``try_memo_extract_joins`` is the production entry, called from
optimizer._rewrite_bottom_up when ``optimizer_use_memo`` is on.  It
returns None — and the caller falls back to the greedy orderer — when any
leaf lacks a row-count estimate or the graph exceeds
``memo_max_reorder_relations``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from presto_tpu.expr.ir import InputRef, RowExpression, input_channels
from presto_tpu.sql.plan import (
    AggregationNode, Column, FilterNode, JoinNode, PlanNode, ProjectNode,
    SemiJoinNode,
)
from presto_tpu.sql.rules import Rule, RuleContext
from presto_tpu.sql.stats import PlanStats, StatsCalculator

_INF = float("inf")


# ---------------------------------------------------------------------------
# Memo
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class GroupRef(PlanNode):
    """Leaf standing for 'any member of group' (GroupReference.java)."""

    group: int
    columns: Tuple[Column, ...]


class Memo:
    """Groups of logically-equivalent plan alternatives.  Members are
    nodes whose children are GroupRefs; inserting a concrete subtree
    recursively rewrites children into groups and deduplicates
    structurally, so shared subtrees land in shared groups."""

    def __init__(self):
        self._members: List[List[PlanNode]] = []
        self._columns: List[Tuple[Column, ...]] = []
        self._index: Dict[object, int] = {}

    def __len__(self) -> int:
        return len(self._members)

    def new_group(self, columns: Tuple[Column, ...]) -> int:
        self._members.append([])
        self._columns.append(tuple(columns))
        return len(self._members) - 1

    def ref(self, gid: int) -> GroupRef:
        return GroupRef(gid, self._columns[gid])

    def members(self, gid: int) -> List[PlanNode]:
        return self._members[gid]

    @staticmethod
    def _key(member: PlanNode):
        try:
            hash(member)
            return member
        except TypeError:  # unhashable payload (e.g. VALUES literals)
            return ("unhashable", id(member))

    def _canonicalize(self, node: PlanNode) -> PlanNode:
        """Children -> GroupRefs (inserting concrete subtrees)."""
        from presto_tpu.sql.optimizer import _replace_sources

        if not node.sources:
            return node
        srcs = [s if isinstance(s, GroupRef) else self.ref(self.insert(s))
                for s in node.sources]
        return _replace_sources(node, srcs)

    def insert(self, node: PlanNode) -> int:
        """Subtree -> group id (existing group when an equal member is
        already registered)."""
        if isinstance(node, GroupRef):
            return node.group
        member = self._canonicalize(node)
        key = self._key(member)
        gid = self._index.get(key)
        if gid is not None:
            return gid
        gid = self.new_group(tuple(member.columns))
        self._members[gid].append(member)
        self._index[key] = gid
        return gid

    def add(self, gid: int, node: PlanNode) -> bool:
        """Add an ALTERNATIVE member to an existing group (rule output);
        returns False when an equal member is already present."""
        member = self._canonicalize(node)
        if any(member == m for m in self._members[gid]):
            return False
        self._members[gid].append(member)
        self._index.setdefault(self._key(member), gid)
        return True


class MemoStatsCalculator(StatsCalculator):
    """Stats derivation through GroupRefs: a group's stats are the
    stats of its FIRST member — logical properties belong to the group,
    not the alternative (the Volcano invariant; Memo.java group stats)."""

    def __init__(self, memo: Memo, metadata=None):
        super().__init__(metadata)
        self.memo = memo
        self._group_stats: Dict[int, PlanStats] = {}

    def _derive(self, node: PlanNode) -> PlanStats:
        if isinstance(node, GroupRef):
            hit = self._group_stats.get(node.group)
            if hit is None:
                # cycle guard: a self-referential group derives unknown
                self._group_stats[node.group] = PlanStats(None)
                hit = self.stats(self.memo.members(node.group)[0])
                self._group_stats[node.group] = hit
            return hit
        return super()._derive(node)


# ---------------------------------------------------------------------------
# Cost model
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CostEstimate:
    """(cpu, memory, network) in estimated bytes touched
    (PlanCostEstimate role)."""

    cpu: float
    memory: float
    network: float

    def __add__(self, other: "CostEstimate") -> "CostEstimate":
        return CostEstimate(self.cpu + other.cpu,
                            self.memory + other.memory,
                            self.network + other.network)

    @property
    def unknown(self) -> bool:
        return self.cpu == _INF


ZERO_COST = CostEstimate(0.0, 0.0, 0.0)
UNKNOWN_COST = CostEstimate(_INF, _INF, _INF)


class CostComparator:
    """Weighted total ordering over CostEstimate (CostComparator.java —
    same default weights)."""

    def __init__(self, cpu_weight: float = 75.0,
                 memory_weight: float = 10.0,
                 network_weight: float = 15.0):
        self.cpu_weight = cpu_weight
        self.memory_weight = memory_weight
        self.network_weight = network_weight

    def total(self, c: CostEstimate) -> float:
        return (c.cpu * self.cpu_weight + c.memory * self.memory_weight
                + c.network * self.network_weight)


def _col_width(t) -> float:
    d = t.display()
    if d.startswith(("varchar", "char")):
        return 16.0
    if d.startswith(("array", "map", "row")):
        return 64.0
    return 8.0


def _row_width(columns) -> float:
    return sum(_col_width(t) for _, t in columns) or 8.0


class CostModel:
    """Per-node local cost from the stats derivation
    (CostCalculatorUsingExchanges.java role): cpu = bytes consumed +
    produced, memory = build-side residency, network = exchange traffic
    per distribution choice."""

    def __init__(self, stats: StatsCalculator, config=None):
        from presto_tpu.config import DEFAULT

        self.stats = stats
        self.config = config or DEFAULT
        # exchange fan-out: tasks a broadcast build must reach
        self.fanout = float(self.config.hash_partition_count or 4)
        self._cumulative: Dict[int, Tuple[PlanNode, CostEstimate]] = {}

    def output_bytes(self, node: PlanNode) -> Optional[float]:
        rc = self.stats.stats(node).row_count
        if rc is None:
            return None
        return rc * _row_width(node.columns)

    def replicated_allowed(self, node: JoinNode) -> bool:
        """join_max_broadcast_table_size analogue: a build side above the
        broadcast row limit may not replicate, whatever the cost says."""
        rc = self.stats.stats(node.right).row_count
        return rc is not None and rc <= self.config.broadcast_join_row_limit

    def join_network(self, node: JoinNode, probe_bytes: float,
                     build_bytes: float) -> float:
        """Exchange traffic of one join: REPLICATED ships the build side
        to every task; PARTITIONED re-hashes both sides once.  An
        undecided join is charged its cheapest admissible choice — the
        one DetermineJoinDistribution will commit to."""
        if node.kind == "cross" or not node.left_keys:
            return build_bytes * self.fanout
        replicated = build_bytes * self.fanout
        partitioned = probe_bytes + build_bytes
        dist = node.distribution
        forced = self.config.join_distribution_type
        if forced == "broadcast":
            dist = "replicated"
        elif forced == "partitioned":
            dist = "partitioned"
        if dist == "replicated":
            return replicated
        if dist == "partitioned":
            return partitioned
        if self.replicated_allowed(node):
            return min(replicated, partitioned)
        return partitioned

    def local_cost(self, node: PlanNode) -> CostEstimate:
        """Cost of this node alone (children excluded); children may be
        GroupRefs when ``stats`` is memo-aware."""
        out = self.output_bytes(node)
        if out is None:
            return UNKNOWN_COST
        if isinstance(node, JoinNode):
            probe = self.output_bytes(node.left)
            build = self.output_bytes(node.right)
            if probe is None or build is None:
                return UNKNOWN_COST
            return CostEstimate(probe + build + out, build,
                                self.join_network(node, probe, build))
        if isinstance(node, SemiJoinNode):
            src = self.output_bytes(node.source)
            filt = self.output_bytes(node.filtering)
            if src is None or filt is None:
                return UNKNOWN_COST
            # filtering side broadcasts (fragmenter policy)
            return CostEstimate(src + filt + out, filt,
                                filt * self.fanout)
        if isinstance(node, AggregationNode):
            src = self.output_bytes(node.sources[0])
            if src is None:
                return UNKNOWN_COST
            return CostEstimate(src + out, out, 0.0)
        if isinstance(node, ProjectNode) and all(
                isinstance(e, InputRef) for e in node.expressions):
            # pure channel permutation: column references, no evaluation
            return ZERO_COST
        return CostEstimate(out, 0.0, 0.0)

    def cumulative(self, node: PlanNode) -> CostEstimate:
        """Recursive cost of a CONCRETE plan (no GroupRefs) — the
        EXPLAIN annotation path."""
        hit = self._cumulative.get(id(node))
        if hit is not None and hit[0] is node:
            return hit[1]
        cost = self.local_cost(node)
        for s in node.sources:
            cost = cost + self.cumulative(s)
        self._cumulative[id(node)] = (node, cost)
        return cost


# ---------------------------------------------------------------------------
# Exploration driver + extraction
# ---------------------------------------------------------------------------

class MemoOptimizer:
    """Runs rules non-destructively over memo groups and extracts the
    cheapest alternative per group (IterativeOptimizer.exploreGroup +
    Memo extraction roles)."""

    def __init__(self, memo: Memo, metadata=None, config=None,
                 stats: Optional[MemoStatsCalculator] = None,
                 cost_model: Optional[CostModel] = None,
                 comparator: Optional[CostComparator] = None):
        self.memo = memo
        self.stats = stats or MemoStatsCalculator(memo, metadata)
        self.cost_model = cost_model or CostModel(self.stats, config)
        self.comparator = comparator or CostComparator()
        # gid -> (cost, member index, materialized plan) | None (cyclic)
        self._best: Dict[int, Optional[Tuple[CostEstimate, int, PlanNode]]] \
            = {}
        self._in_progress: set = set()

    # -- exploration ----------------------------------------------------
    def _bindings(self, member: PlanNode,
                  chosen_only: bool = False) -> Iterator[PlanNode]:
        """The member itself, plus one variant per (child slot, child
        member) with that GroupRef resolved one level — enough for the
        depth-2 patterns rules.py matches (Matcher-through-Lookup role).
        ``chosen_only`` binds each child slot to its group's extracted
        winner only (bounded exploration of the best tree)."""
        from presto_tpu.sql.optimizer import _replace_sources

        yield member
        srcs = list(member.sources)
        for i, s in enumerate(srcs):
            if not isinstance(s, GroupRef):
                continue
            alts = self.memo.members(s.group)
            if chosen_only:
                hit = self._best.get(s.group)
                alts = [alts[hit[1]]] if hit else alts[:1]
            for alt in alts:
                bound = list(srcs)
                bound[i] = alt
                yield _replace_sources(member, bound)

    def explore(self, ctx: RuleContext, rules: Sequence[Rule],
                gids: Optional[Sequence[int]] = None,
                budget: int = 500, chosen_only: bool = False) -> int:
        """Apply ``rules`` over group members to fixpoint; every match
        ADDS an alternative member (originals stay — non-destructive,
        unlike rules.iterative_optimize).  ``chosen_only`` visits only
        each group's extracted winner (and binds winners below it), the
        bounded post-extraction pass the join enumerator uses on big
        memos.  Returns members added."""
        added = 0
        progress = True
        while progress and added < budget:
            progress = False
            targets = (list(gids) if gids is not None
                       else list(range(len(self.memo))))
            for gid in targets:
                members = self.memo.members(gid)
                if chosen_only:
                    hit = self._best.get(gid)
                    members = [members[hit[1]]] if hit else list(members)
                else:
                    members = list(members)
                for member in members:
                    for binding in self._bindings(member, chosen_only):
                        for rule in rules:
                            if added >= budget:
                                return added
                            out = rule.apply(binding, ctx)
                            if out is not None and self.memo.add(gid, out):
                                added += 1
                                progress = True
        return added

    # -- extraction -----------------------------------------------------
    def invalidate(self) -> None:
        self._best.clear()

    def best(self, gid: int
             ) -> Optional[Tuple[CostEstimate, int, PlanNode]]:
        """(cost, member index, materialized plan) of the cheapest
        alternative; ties go to the LATER member (rule outputs beat the
        originals they rewrote)."""
        from presto_tpu.sql.optimizer import _replace_sources

        hit = self._best.get(gid)
        if hit is not None or gid in self._best:
            return hit
        if gid in self._in_progress:
            return None
        self._in_progress.add(gid)
        try:
            winner: Optional[Tuple[CostEstimate, int, PlanNode]] = None
            winner_total = _INF
            for idx, member in enumerate(self.memo.members(gid)):
                cost = self.cost_model.local_cost(member)
                srcs = []
                dead = False
                for s in member.sources:
                    if isinstance(s, GroupRef):
                        sub = self.best(s.group)
                        if sub is None:
                            dead = True
                            break
                        cost = cost + sub[0]
                        srcs.append(sub[2])
                    else:
                        srcs.append(s)
                if dead:
                    continue
                plan = _replace_sources(member, srcs) if srcs else member
                total = self.comparator.total(cost)
                if winner is None or total < winner_total or (
                        total == winner_total and idx > winner[1]):
                    winner = (cost, idx, plan)
                    winner_total = total
            self._best[gid] = winner
            return winner
        finally:
            self._in_progress.discard(gid)

    def best_groups(self, gid: int) -> List[int]:
        """Groups reachable through the chosen members of ``gid``'s best
        tree (must be called after best())."""
        out: List[int] = []
        seen = set()
        stack = [gid]
        while stack:
            g = stack.pop()
            if g in seen:
                continue
            seen.add(g)
            out.append(g)
            hit = self._best.get(g)
            if not hit:
                continue
            member = self.memo.members(g)[hit[1]]
            for s in member.sources:
                if isinstance(s, GroupRef):
                    stack.append(s.group)
        return out


# ---------------------------------------------------------------------------
# Exploration rule: DetermineJoinDistribution
# ---------------------------------------------------------------------------

class DetermineJoinDistribution(Rule):
    """REPLICATED vs PARTITIONED chosen by exchange cost
    (DetermineJoinDistributionType.java:50 role) instead of the
    fragmenter's bare row-count threshold — the threshold survives only
    as the broadcast admissibility cap.  Produces an annotated
    alternative member; extraction's later-member tie-break commits it."""

    name = "determine_join_distribution"

    def __init__(self, cost_model: CostModel):
        self.cost_model = cost_model

    def apply(self, node: PlanNode, ctx: RuleContext) -> Optional[PlanNode]:
        if not (isinstance(node, JoinNode) and node.kind != "cross"
                and node.left_keys and node.distribution is None):
            return None
        if self.cost_model.config.join_distribution_type != "automatic":
            return None       # session property forces the distribution
        probe = self.cost_model.output_bytes(node.left)
        build = self.cost_model.output_bytes(node.right)
        if probe is None or build is None:
            return None
        replicated = build * self.cost_model.fanout
        partitioned = probe + build
        dist = ("replicated"
                if self.cost_model.replicated_allowed(node)
                and replicated <= partitioned else "partitioned")
        return dataclasses.replace(node, distribution=dist)


# ---------------------------------------------------------------------------
# Exploration rule: ReorderJoins (bounded bushy enumeration)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Layout:
    """Canonical channel layout of one relation subset: leaves in
    ascending index order, concatenated."""

    leaves: List[int]
    pos: Dict[Tuple[int, int], int]     # (leaf, local ch) -> position
    columns: Tuple[Column, ...]


class JoinEnumerator:
    """ReorderJoins.java's JoinEnumerator role over optimizer.JoinGraph:
    every connected relation subset becomes ONE memo group whose members
    are the valid (edge-crossing, connected) partitions of that subset
    into probe x build — bushy shapes included.  Cost extraction over
    the memo IS the dynamic program: cheapest per subset, reused by
    every containing subset."""

    def __init__(self, graph, optimizer: MemoOptimizer, config):
        self.graph = graph
        self.opt = optimizer
        self.memo = optimizer.memo
        self.config = config
        n = len(graph.nodes)
        self.n = n
        self.adj = [0] * n
        for la, _, lb, _ in graph.edges:
            self.adj[la] |= 1 << lb
            self.adj[lb] |= 1 << la
        # residual conjunct -> mask of referenced leaves
        self.res_masks: List[int] = []
        for c in graph.residual:
            m = 0
            for ch in input_channels(c):
                m |= 1 << graph.leaf_of(ch)
            self.res_masks.append(m)
        self._layouts: Dict[int, _Layout] = {}
        self._groups: Dict[int, int] = {}
        self._conn: Dict[int, bool] = {}

    # -- bitmask helpers ------------------------------------------------
    def _bits(self, mask: int) -> List[int]:
        return [i for i in range(self.n) if mask >> i & 1]

    def _connected(self, mask: int) -> bool:
        hit = self._conn.get(mask)
        if hit is not None:
            return hit
        bits = self._bits(mask)
        seen = 1 << bits[0]
        frontier = seen
        while frontier:
            nxt = 0
            for i in self._bits(frontier):
                nxt |= self.adj[i] & mask & ~seen
            seen |= nxt
            frontier = nxt
        out = seen == mask
        self._conn[mask] = out
        return out

    def layout(self, mask: int) -> _Layout:
        hit = self._layouts.get(mask)
        if hit is not None:
            return hit
        leaves = self._bits(mask)
        pos: Dict[Tuple[int, int], int] = {}
        cols: List[Column] = []
        for li in leaves:
            for j, col in enumerate(self.graph.nodes[li].columns):
                pos[(li, j)] = len(cols)
                cols.append(col)
        out = _Layout(leaves, pos, tuple(cols))
        self._layouts[mask] = out
        return out

    # -- group construction ---------------------------------------------
    def group(self, mask: int) -> int:
        """Memo group holding every enumerated alternative for ``mask``."""
        hit = self._groups.get(mask)
        if hit is not None:
            return hit
        bits = self._bits(mask)
        if len(bits) == 1:
            gid = self.memo.insert(self.graph.nodes[bits[0]])
            self._groups[mask] = gid
            return gid
        gid = self.memo.new_group(self.layout(mask).columns)
        self._groups[mask] = gid        # register before recursing
        sub = (mask - 1) & mask
        while sub:
            other = mask ^ sub
            if (other and self._cross_edges(sub, other)
                    and self._connected(sub) and self._connected(other)):
                self.memo.add(gid, self._member(mask, sub, other))
            sub = (sub - 1) & mask
        return gid

    def _cross_edges(self, a: int, b: int
                     ) -> List[Tuple[int, int, int, int]]:
        """Edges crossing the (a, b) partition, oriented a-side first."""
        out = []
        for la, ca, lb, cb in self.graph.edges:
            if a >> la & 1 and b >> lb & 1:
                out.append((la, ca, lb, cb))
            elif b >> la & 1 and a >> lb & 1:
                out.append((lb, cb, la, ca))
        return out

    def _member(self, mask: int, a: int, b: int) -> PlanNode:
        """One alternative: probe=group(a) JOIN build=group(b), residuals
        first coverable here, then the canonical-order projection that
        keeps every member of the group schema-identical."""
        lay_a, lay_b = self.layout(a), self.layout(b)
        lks, rks = [], []
        for la, ca, lb, cb in self._cross_edges(a, b):
            lks.append(lay_a.pos[(la, ca)])
            rks.append(lay_b.pos[(lb, cb)])
        concat = lay_a.columns + lay_b.columns
        node: PlanNode = JoinNode(
            "inner", self.memo.ref(self.group(a)),
            self.memo.ref(self.group(b)), tuple(lks), tuple(rks), concat)

        def concat_pos(leaf: int, local: int) -> int:
            if a >> leaf & 1:
                return lay_a.pos[(leaf, local)]
            return len(lay_a.columns) + lay_b.pos[(leaf, local)]

        ready: List[RowExpression] = []
        for c, rm in zip(self.graph.residual, self.res_masks):
            if rm and rm & mask == rm and rm & a != rm and rm & b != rm:
                ready.append(self._remap_residual(c, concat_pos))
        if ready:
            from presto_tpu.sql.optimizer import and_all

            node = FilterNode(node, and_all(ready))

        lay = self.layout(mask)
        perm = [concat_pos(li, j) for li in lay.leaves
                for j in range(len(self.graph.nodes[li].columns))]
        if perm != list(range(len(perm))):
            node = ProjectNode(
                node,
                tuple(InputRef(p, concat[p][1]) for p in perm),
                lay.columns)
        return node

    def _remap_residual(self, c: RowExpression, concat_pos) -> RowExpression:
        from presto_tpu.sql.optimizer import remap

        mapping = {}
        for ch in input_channels(c):
            leaf = self.graph.leaf_of(ch)
            mapping[ch] = concat_pos(leaf, ch - self.graph.offsets[leaf])
        return remap(c, mapping)

    # -- top-level plan ---------------------------------------------------
    def plan(self, ctx: RuleContext
             ) -> Optional[Tuple[PlanNode, Dict[Tuple[int, int], int]]]:
        """Best join tree + (leaf, local ch) -> output channel map.
        Disconnected graphs enumerate per component; components then
        cross-join left-deep, largest first (the greedy anchor rule)."""
        from presto_tpu.sql.optimizer import and_all
        from presto_tpu.sql.rules import DEFAULT_RULES

        full = (1 << self.n) - 1
        comps: List[int] = []
        rest = full
        while rest:
            seed = rest & -rest
            comp = seed
            frontier = seed
            while frontier:
                nxt = 0
                for i in self._bits(frontier):
                    nxt |= self.adj[i] & rest & ~comp
                comp |= nxt
                frontier = nxt
            comps.append(comp)
            rest &= ~comp

        comp_gids = [self.group(m) for m in comps]
        for gid in comp_gids:
            if self.opt.best(gid) is None:
                return None
        # exploration pass over the winning trees only: the existing
        # rules plus the distribution annotator run non-destructively;
        # re-extraction commits annotated members on cost ties
        explore_gids: List[int] = []
        for gid in comp_gids:
            explore_gids.extend(self.opt.best_groups(gid))
        rules = tuple(DEFAULT_RULES) + (
            DetermineJoinDistribution(self.opt.cost_model),)
        self.opt.explore(ctx, rules, gids=explore_gids, chosen_only=True)
        self.opt.invalidate()

        extracted = []
        for m, gid in zip(comps, comp_gids):
            hit = self.opt.best(gid)
            if hit is None:
                return None
            extracted.append((m, hit[0], hit[2]))
        # largest estimated output anchors the cross-join chain
        def comp_rows(m: int) -> float:
            rc = self.opt.stats.stats(
                self.memo.ref(self._groups[m])).row_count
            return -1.0 if rc is None else rc

        extracted.sort(key=lambda t: (-comp_rows(t[0]), t[0]))

        chan_map: Dict[Tuple[int, int], int] = {}
        current: Optional[PlanNode] = None
        placed_mask = 0
        # residuals fully inside one component were placed during
        # enumeration; spanning ones place along the cross-join chain and
        # zero-channel (constant) ones apply at the very top
        pending = [(c, rm) for c, rm in zip(self.graph.residual,
                                            self.res_masks)
                   if not any(rm and rm & m == rm for m in comps)]
        for m, _cost, plan in extracted:
            base = 0 if current is None else len(current.columns)
            lay = self.layout(m)
            for key, p in lay.pos.items():
                chan_map[key] = base + p
            if current is None:
                current = plan
            else:
                current = JoinNode("cross", current, plan, (), (),
                                   tuple(current.columns) + lay.columns)
            placed_mask |= m
            ready = []
            still = []
            for c, rm in pending:
                if rm and rm & placed_mask == rm:
                    from presto_tpu.sql.optimizer import remap

                    mapping = {
                        ch: chan_map[(self.graph.leaf_of(ch),
                                      ch - self.graph.offsets[
                                          self.graph.leaf_of(ch)])]
                        for ch in input_channels(c)}
                    ready.append(remap(c, mapping))
                else:
                    still.append((c, rm))
            pending = still
            if ready:
                current = FilterNode(current, and_all(ready))
        # zero-channel residuals (constant predicates) at the top
        consts = [c for c, rm in pending if not rm]
        if consts:
            current = FilterNode(current, and_all(consts))
        return current, chan_map


# ---------------------------------------------------------------------------
# Production entries
# ---------------------------------------------------------------------------

def try_memo_extract_joins(filter_node: FilterNode, metadata,
                           config) -> Optional[PlanNode]:
    """Memo-based replacement for optimizer.extract_joins.  Returns None
    (caller falls back to the greedy orderer) when any leaf lacks a
    row-count estimate or the graph exceeds the enumeration bound."""
    from presto_tpu.sql.optimizer import build_join_graph, restore_leaf_order

    graph = build_join_graph(filter_node)
    n = len(graph.nodes)
    if n < 2 or n > config.memo_max_reorder_relations:
        return None
    memo = Memo()
    stats = MemoStatsCalculator(memo, metadata)
    for leaf in graph.nodes:
        if stats.stats(leaf).row_count is None:
            return None
    opt = MemoOptimizer(memo, metadata=metadata, config=config, stats=stats)
    enumerator = JoinEnumerator(graph, opt, config)
    out = enumerator.plan(RuleContext(metadata, config))
    if out is None:
        return None
    current, chan_map = out
    return restore_leaf_order(graph, current, chan_map)


def cost_annotator(metadata, config=None):
    """format_plan annotator: per-node estimated rows + cumulative
    (cpu, memory, network) — the EXPLAIN cost surface
    (PlanPrinter.formatPlanNodeStats role)."""
    stats = StatsCalculator(metadata)
    model = CostModel(stats, config)

    def annotate(node: PlanNode) -> str:
        st = stats.stats(node)
        if st.row_count is None:
            return ""
        cost = model.cumulative(node)
        if cost.unknown:
            return f"  {{rows: {st.row_count:.0f}}}"
        return (f"  {{rows: {st.row_count:.0f}, cpu: {cost.cpu:.3g}, "
                f"mem: {cost.memory:.3g}, net: {cost.network:.3g}}}")

    return annotate
