"""SQL front end: lexer -> parser -> AST -> analyzer -> logical plan.

The reference parses with an ANTLR4 grammar
(presto-parser/src/main/antlr4/io/prestosql/sql/parser/SqlBase.g4, 819
lines) into ~170 AST node classes, analyzes them
(presto-main/.../sql/analyzer/StatementAnalyzer.java:243), and plans into a
PlanNode tree (presto-main/.../sql/planner/LogicalPlanner.java:176).  This
package is the same pipeline built fresh: a hand-written recursive-descent
parser over the SQL subset the engine executes (the full TPC-H/TPC-DS
query shape), a scope-based analyzer, and a logical planner producing the
PlanNode IR in ``plan.py``.
"""

from presto_tpu.sql.parser import parse_statement  # noqa: F401
