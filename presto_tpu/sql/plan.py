"""Logical plan IR.

Channel-based analogue of the reference's PlanNode tree
(presto-main/.../sql/planner/plan/, 49 node types; this subset covers the
engine's executable shapes).  Unlike the reference's symbol-based plans,
expressions here reference *channel indices* of the child's output — the
planner resolves names once, and optimizer rewrites remap channels
explicitly (the HashGenerationOptimizer-style passes operate the same way).

Every node carries ``columns``: the output schema as (name, Type) pairs.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.expr.functions import AggSpec
from presto_tpu.expr.ir import RowExpression

Column = Tuple[str, T.Type]


class PlanNode:
    columns: Tuple[Column, ...]
    sources: Tuple["PlanNode", ...] = ()

    @property
    def types(self) -> List[T.Type]:
        return [t for _, t in self.columns]

    @property
    def names(self) -> List[str]:
        return [n for n, _ in self.columns]


D = dataclasses.dataclass


@D(frozen=True)
class TableScanNode(PlanNode):
    """Leaf scan (TableScanNode.java analogue); ``column_names`` are the
    connector-side names in output order."""

    catalog: str
    table: str
    column_names: Tuple[str, ...]
    columns: Tuple[Column, ...]


@D(frozen=True)
class ValuesNode(PlanNode):
    columns: Tuple[Column, ...]
    rows: Tuple[Tuple[object, ...], ...]


@D(frozen=True)
class FilterNode(PlanNode):
    source: PlanNode
    predicate: RowExpression

    @property
    def columns(self):  # type: ignore[override]
        return self.source.columns

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class ProjectNode(PlanNode):
    source: PlanNode
    expressions: Tuple[RowExpression, ...]
    columns: Tuple[Column, ...]

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class PlanAggregate:
    """One aggregate: resolved spec + input channel (None = count(*))."""

    spec: AggSpec
    channel: Optional[int]
    distinct: bool = False
    output_name: str = ""


@D(frozen=True)
class AggregationNode(PlanNode):
    source: PlanNode
    group_channels: Tuple[int, ...]
    aggregates: Tuple[PlanAggregate, ...]
    columns: Tuple[Column, ...]  # group keys then aggregate results
    step: str = "single"         # single | partial | final

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class JoinNode(PlanNode):
    """Equi-join (JoinNode.java analogue).  Output = left columns then
    right columns.  ``residual`` is evaluated over that concatenated
    channel space against matched pairs (JoinFilterFunction role)."""

    kind: str                    # inner | left | right | full | cross
    left: PlanNode
    right: PlanNode
    left_keys: Tuple[int, ...]
    right_keys: Tuple[int, ...]
    columns: Tuple[Column, ...]
    residual: Optional[RowExpression] = None
    # cost-chosen exchange placement (JoinNode.DistributionType role):
    # 'replicated' broadcasts the build side, 'partitioned' co-hash-
    # partitions both sides; None = let the fragmenter's stats threshold
    # decide (pre-CBO behavior)
    distribution: Optional[str] = None

    @property
    def sources(self):  # type: ignore[override]
        return (self.left, self.right)


@D(frozen=True)
class SemiJoinNode(PlanNode):
    """Filters ``source`` rows by key membership in ``filtering``
    (SemiJoinNode + the consuming filter, fused).  Output = source columns.
    ``residual`` (if any) is evaluated over [source columns, filtering
    columns] per candidate pair — the correlated-EXISTS residual."""

    source: PlanNode
    filtering: PlanNode
    source_keys: Tuple[int, ...]
    filtering_keys: Tuple[int, ...]
    negated: bool = False        # NOT IN / NOT EXISTS (anti join)
    residual: Optional[RowExpression] = None
    # NOT IN three-valued-logic semantics (vs NOT EXISTS): a NULL probe
    # key or any NULL in a non-empty filtering side yields UNKNOWN ->
    # row excluded; an EMPTY filtering side keeps every row
    null_aware: bool = False

    @property
    def columns(self):  # type: ignore[override]
        return self.source.columns

    @property
    def sources(self):  # type: ignore[override]
        return (self.source, self.filtering)


@D(frozen=True)
class PlanWindowFunction:
    """One windowed function over a shared (partition, order, frame) spec.

    ``name`` is the window/aggregate function; ``arg_channels`` index the
    source's channels; frame fields are None for ranking functions (which
    ignore frames).  ``offset``/``default_channel`` serve lag/lead/ntile/
    nth_value's extra scalar arguments."""

    name: str
    arg_channels: Tuple[int, ...]
    result_type: T.Type
    frame_unit: str = "range"            # rows | range
    frame_start: str = "unbounded_preceding"
    frame_end: str = "current"
    frame_start_offset: Optional[int] = None
    frame_end_offset: Optional[int] = None
    offset: Optional[int] = None         # lag/lead/nth_value k, ntile n
    default_channel: Optional[int] = None  # lag/lead default value


@D(frozen=True)
class WindowNode(PlanNode):
    """Window functions over a shared partition/order spec
    (WindowNode.java analogue).  Output = source columns + one column per
    function."""

    source: PlanNode
    partition_channels: Tuple[int, ...]
    order_keys: Tuple[Tuple[int, bool, Optional[bool]], ...]
    functions: Tuple[PlanWindowFunction, ...]
    columns: Tuple[Column, ...]

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class UnnestNode(PlanNode):
    """UNNEST over source rows (UnnestNode.java / UnnestOperator.java:39).

    ``replicate_channels`` pass through repeated per element;
    ``unnest_channels`` are ARRAY/MAP columns expanded positionally (zip to
    the longest, null-padding shorter ones); ``ordinality`` appends the
    1-based element index.  ``columns`` = replicated + per-unnest outputs
    (map -> key,value; array(row) -> one column per field) + ordinality.
    """

    source: PlanNode
    replicate_channels: Tuple[int, ...]
    unnest_channels: Tuple[int, ...]
    ordinality: bool
    columns: Tuple[Column, ...]
    # LEFT JOIN UNNEST: rows with empty/NULL containers still emit one
    # output row with NULL unnest columns
    outer: bool = False

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class UnionNode(PlanNode):
    """UNION ALL of same-width inputs (UnionNode.java analogue); DISTINCT
    and INTERSECT/EXCEPT are planned as aggregations/semijoins above this."""

    inputs: Tuple[PlanNode, ...]
    columns: Tuple[Column, ...]

    @property
    def sources(self):  # type: ignore[override]
        return self.inputs


@D(frozen=True)
class SortNode(PlanNode):
    source: PlanNode
    sort_keys: Tuple[Tuple[int, bool, Optional[bool]], ...]
    # (channel, ascending, nulls_first)

    @property
    def columns(self):  # type: ignore[override]
        return self.source.columns

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class LimitNode(PlanNode):
    source: PlanNode
    count: int

    @property
    def columns(self):  # type: ignore[override]
        return self.source.columns

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class EnforceSingleRowNode(PlanNode):
    """Scalar subquery guard (EnforceSingleRowOperator analogue): errors if
    the source yields >1 row; yields a single all-NULL row if empty."""

    source: PlanNode

    @property
    def columns(self):  # type: ignore[override]
        return self.source.columns

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class RemoteSourceNode(PlanNode):
    """Reads the output of other fragments over the exchange protocol
    (RemoteSourceNode / ExchangeOperator.java:36 analogue).  Appears only
    inside PlanFragments produced by the fragmenter."""

    fragment_ids: Tuple[int, ...]
    columns: Tuple[Column, ...]


@D(frozen=True)
class RemoteMergeNode(PlanNode):
    """Order-preserving remote source: every producer task emits a
    pre-sorted stream and this node k-way merges them (MergeOperator
    .java:45 + ExchangeOperator's ORDER BY variant).  ``limit`` stops
    the merge early for distributed TopN."""

    fragment_ids: Tuple[int, ...]
    sort_keys: Tuple[Tuple[int, bool, Optional[bool]], ...]
    columns: Tuple[Column, ...]
    limit: Optional[int] = None


@D(frozen=True)
class TableWriterNode(PlanNode):
    """Streams source rows into a per-task connector staging sink and
    emits ONE (rows, fragment) row (TableWriterOperator.java:58 role);
    the matching TableFinishNode commits.  Writer fragments are
    'scaled'-partitioned (SCALED_WRITER_DISTRIBUTION,
    SystemPartitioningHandle.java:62)."""

    source: PlanNode
    catalog: str
    table: str
    write_id: str
    columns: Tuple[Column, ...]  # (("rows", BIGINT), ("fragment", VARCHAR))

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class TableFinishNode(PlanNode):
    """Collects every writer task's (rows, fragment) row, commits the
    write atomically via Connector.finish_write, and emits the total row
    count (TableFinishOperator.java:46 role)."""

    source: PlanNode
    catalog: str
    table: str
    write_id: str
    columns: Tuple[Column, ...]  # (("rows", BIGINT),)

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


@D(frozen=True)
class OutputNode(PlanNode):
    source: PlanNode
    columns: Tuple[Column, ...]  # output names (possibly renamed)

    @property
    def sources(self):  # type: ignore[override]
        return (self.source,)


def format_plan(node: PlanNode, indent: int = 0, annotator=None) -> str:
    """EXPLAIN-style text rendering (planPrinter role).  ``annotator``
    (node -> str) appends per-node text — the EXPLAIN cost/stats surface
    (sql/memo.py cost_annotator)."""
    pad = "  " * indent
    name = type(node).__name__.replace("Node", "")
    detail = ""
    if isinstance(node, TableScanNode):
        detail = f" {node.catalog}.{node.table}"
    elif isinstance(node, FilterNode):
        detail = f" [{node.predicate}]"
    elif isinstance(node, ProjectNode):
        detail = " [" + ", ".join(map(str, node.expressions)) + "]"
    elif isinstance(node, AggregationNode):
        aggs = ", ".join(
            f"{a.spec.name}(#{a.channel if a.channel is not None else '*'})"
            + ("/distinct" if a.distinct else "")
            for a in node.aggregates)
        detail = f" keys={list(node.group_channels)} [{aggs}]"
        if node.step != "single":
            detail += f" step={node.step}"
    elif isinstance(node, JoinNode):
        detail = (f" {node.kind} on {list(node.left_keys)}="
                  f"{list(node.right_keys)}")
        if node.distribution is not None:
            detail += f" dist={node.distribution}"
        if node.residual is not None:
            detail += f" residual=[{node.residual}]"
    elif isinstance(node, SemiJoinNode):
        detail = (f" {'anti' if node.negated else 'semi'} on "
                  f"{list(node.source_keys)}={list(node.filtering_keys)}")
    elif isinstance(node, SortNode):
        detail = " " + str([(c, "asc" if a else "desc")
                            for c, a, _ in node.sort_keys])
    elif isinstance(node, LimitNode):
        detail = f" {node.count}"
    elif isinstance(node, (TableWriterNode, TableFinishNode)):
        detail = f" {node.catalog}.{node.table}"
    out = f"{pad}{name}{detail}  => {[n for n, _ in node.columns]}"
    if annotator is not None:
        out += annotator(node)
    out += "\n"
    for s in node.sources:
        out += format_plan(s, indent + 1, annotator)
    return out
