"""Timed query spans: where a query's wall clock went.

The airlift stage-timing role (the reference attributes wall time to
dispatch/queue/planning/scheduling phases on the coordinator and to
per-stage/task execution on the workers; the web UI renders it as the
query timeline).  Here the coordinator records a ``QuerySpan`` tree from
timestamps it already owns:

    query
    ├── queue            (create -> admission)
    ├── parse / analyze / optimize / fragment / schedule
    ├── execute          (drain span)
    └── stage-{fid}
        └── task {task_id} (attempt aN)   one span per task attempt

Every span carries the query's trace token as its trace id, wall-clock
``start``/``end`` (epoch seconds), and nests inside its parent (the
builder clamps children into the query window, so ``end >= start``
always holds).  The tree is served at ``/v1/query/{id}/spans``,
serialized into ``QueryCompletedEvent``/query.json, and rendered by
``tools/query_profile.py`` as the ASCII timeline.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class QuerySpan:
    """One timed span; ``kind`` is query | phase | stage | task."""

    name: str
    kind: str
    start: float
    end: float
    trace_token: str = ""
    attributes: Dict = dataclasses.field(default_factory=dict)
    children: List["QuerySpan"] = dataclasses.field(default_factory=list)

    def as_dict(self) -> Dict:
        return {
            "name": self.name, "kind": self.kind,
            "start": round(self.start, 6), "end": round(self.end, 6),
            "durationS": round(max(self.end - self.start, 0.0), 6),
            "traceToken": self.trace_token,
            "attributes": dict(self.attributes),
            "children": [c.as_dict() for c in self.children],
        }


#: coordinator phase order in the rendered timeline.  "lower" and
#: "compile" exist only for device-exchange queries that BUILT their
#: SPMD program this run (trace+lower wall vs XLA-compile wall, the
#: kernelcache.timed_first_call attribution); a program-cache hit
#: records neither and its query reports compile_ns=0.
PHASES = ("queue", "parse", "analyze", "optimize", "fragment", "schedule",
          "lower", "compile", "execute")


def _clamp(start: float, end: float, lo: float, hi: float
           ) -> Tuple[float, float]:
    start = min(max(start, lo), hi)
    end = min(max(end, start), hi)
    return start, end


def _attempt_of(task_id: str) -> int:
    """Attempt number from a task id (``{base}aN`` suffix; 0 if none)."""
    tail = task_id.rsplit(".", 1)[-1]
    if "a" in tail:
        try:
            return int(tail.rsplit("a", 1)[1])
        except ValueError:
            return 0
    return 0


def build_span_tree(query_id: str, trace_token: str,
                    create_time: float, end_time: Optional[float],
                    marks: Dict[str, Tuple[float, float]],
                    task_stats: Dict, admit_time: Optional[float] = None,
                    now: Optional[float] = None) -> Dict:
    """Assemble the span tree from coordinator-owned timestamps.

    ``marks`` holds per-phase (start, end) recorded by the query thread;
    ``task_stats`` is the {fid: [TaskStats dict]} rollup (live sampler
    mid-query, final collection after) whose per-task start/end times
    become the stage/task-attempt spans."""
    import time as _time

    t_now = now if now is not None else _time.time()
    q_end = end_time if end_time is not None else t_now
    q_end = max(q_end, create_time)
    root = QuerySpan(query_id, "query", create_time, q_end, trace_token)
    if admit_time is not None and admit_time > create_time:
        s, e = _clamp(create_time, admit_time, create_time, q_end)
        root.children.append(
            QuerySpan("queue", "phase", s, e, trace_token))
    for name in PHASES:
        if name not in marks:
            continue
        s, e = _clamp(*marks[name], create_time, q_end)
        root.children.append(QuerySpan(name, "phase", s, e, trace_token))
    for fid in sorted(task_stats, key=lambda k: int(k)):
        tss = [ts for ts in task_stats[fid] if ts.get("start_time")]
        if not tss:
            continue
        s0 = min(ts["start_time"] for ts in tss)
        e0 = max(ts.get("end_time") or t_now for ts in tss)
        s0, e0 = _clamp(s0, e0, create_time, q_end)
        stage = QuerySpan(f"stage-{fid}", "stage", s0, e0, trace_token,
                          attributes={"fragmentId": int(fid),
                                      "tasks": len(tss)})
        for ts in tss:
            s, e = _clamp(ts["start_time"],
                          ts.get("end_time") or t_now,
                          s0, e0)
            tid = ts.get("task_id", "?")
            stage.children.append(QuerySpan(
                tid, "task", s, e, trace_token,
                attributes={"attempt": _attempt_of(tid),
                            "state": ts.get("state", ""),
                            "outputRows": ts.get("output_rows", 0),
                            "jitCompileNs": ts.get("jit_compile_ns", 0)}))
        root.children.append(stage)
    return root.as_dict()


def validate_span_tree(tree: Dict) -> List[str]:
    """Structural checks (tests + query_profile --check): every child
    nests inside its parent and every span has end >= start.  Returns a
    list of violations (empty = valid)."""
    errors: List[str] = []

    def walk(node: Dict, lo: float, hi: float) -> None:
        s, e = node["start"], node["end"]
        if e < s:
            errors.append(f"{node['name']}: end {e} < start {s}")
        if s < lo - 1e-6 or e > hi + 1e-6:
            errors.append(
                f"{node['name']}: [{s}, {e}] outside parent [{lo}, {hi}]")
        for c in node.get("children", []):
            walk(c, s, e)

    walk(tree, tree["start"], tree["end"])
    return errors


def render_span_tree(tree: Dict, width: int = 40) -> List[str]:
    """ASCII timeline of the span tree (tools/query_profile.py): one
    bar per span, positioned within the query window."""
    t0, t1 = tree["start"], tree["end"]
    total = max(t1 - t0, 1e-6)
    lines = [f"span timeline ({total * 1000:.1f} ms total, "
             f"trace={tree.get('traceToken', '')})"]

    def bar(s: float, e: float) -> str:
        lo = int((s - t0) / total * width)
        hi = max(int((e - t0) / total * width), lo + 1)
        hi = min(hi, width)
        lo = min(lo, hi - 1)
        return " " * lo + "=" * (hi - lo) + " " * (width - hi)

    def walk(node: Dict, depth: int) -> None:
        label = ("  " * depth + node["name"])[:30]
        lines.append(
            f"  {label:<30} |{bar(node['start'], node['end'])}| "
            f"{node['durationS'] * 1000:>9.1f} ms")
        for c in node.get("children", []):
            walk(c, depth + 1)

    walk(tree, 0)
    return lines
