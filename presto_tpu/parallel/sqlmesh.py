"""SQL on the device mesh: the whole distributed query as ONE SPMD program.

This is the wiring the reference achieves with AddExchanges choosing a
partitioning per subtree (presto-main/.../sql/planner/optimizations/
AddExchanges.java:114) and NodePartitioningManager binding partitions to
nodes (sql/planner/NodePartitioningManager.java:53): here the fragmenter's
DistributedPlan is lowered onto a ``jax.sharding.Mesh`` so that

- every 'source' / 'hash' fragment runs replicated over the mesh shards,
  each shard holding its slice of the rows,
- every fragment boundary becomes an ICI collective chosen by the
  producer's ``output_partitioning`` — 'hash' -> ``all_to_all``
  repartition (P1), 'broadcast' -> ``all_gather`` (P2), 'single' ->
  gather (P4),
- and the ENTIRE fragment DAG traces into a single ``shard_map``-ped,
  jitted XLA program, so exchanges overlap with compute and no
  serialize/HTTP/deserialize hop exists inside a slice.  (The HTTP data
  plane in presto_tpu.server remains the cross-slice / elastic tier;
  this module is the intra-slice fast path.)

Row representation per shard: fixed-capacity padded columns plus a `live`
mask (no compaction on filter — dead rows are masked, the mask fuses into
the aggregation/join kernels).  Static capacities derive from host-known
row counts; joins can exceed their estimate, which sets a per-shard
overflow flag and the host re-runs at a doubled capacity bucket (the
distributed recompile-on-bucket-change policy, same as the local kernels).

Unsupported shapes (window functions, nested types, distinct aggregates,
host-evaluated string paths) raise ``MeshUnsupported`` — callers fall back
to the operator tier, mirroring how the reference falls back from grouped
to ungrouped execution when a plan shape does not qualify.

Telemetry is part of the traced program (PR 12): per-fragment, per-shard
counters — scan input rows, fragment output rows, rows/bytes received
through every boundary collective, and a peak live-intermediate estimate
— ride OUT of the SPMD program as one extra int64 vector output, so the
coordinator can fold a mesh query into the same ``TaskStats ->
StageStats -> QueryStats`` rollup an HTTP query gets (run_info()
["per_shard"]).  With ``mesh_progress_beacons`` on, every boundary also
fires a ``jax.debug.callback`` beacon (parallel/beacons.py) so progress
is observable MID-program; off traces a beacon-free program (PR 11
exactly).  Compiled whole-query programs live in the shared
``kernelcache`` registry ("mesh_program"), so cross-query hits/misses
and build wall (trace+lower vs XLA compile, via ``timed_first_call``)
surface on /metrics like every other kernel cache.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import (
    Batch, Column, Dictionary, batch_from_pylist, concat_batches,
    next_bucket,
)
from presto_tpu.config import DEFAULT, EngineConfig
from presto_tpu.connectors.api import ConnectorRegistry
from presto_tpu.expr.compile import ExprCompiler, needs_host_path
from presto_tpu.expr.ir import InputRef, RowExpression
from presto_tpu.sql.plan import (
    AggregationNode, EnforceSingleRowNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanNode, ProjectNode, RemoteMergeNode, RemoteSourceNode,
    SemiJoinNode, SortNode, TableScanNode, UnionNode, UnnestNode,
    ValuesNode, WindowNode,
)

_MESH_PRIMS = ("sum", "count", "min", "max")

# compiled whole-query SPMD programs, shared across runners and keyed
# (runner serial, sql) — a named kernelcache so program-cache hits,
# misses, and compile wall land on /metrics (the generated-class-cache
# role at whole-query granularity)
from presto_tpu import kernelcache as _kc  # noqa: E402

_PROGRAM_CACHE = _kc.new_cache("mesh_program")

#: fragments actually traced/lowered into SPMD programs, process-wide —
#: the mesh-tier mirror of ``sql.physical.FRAGMENTS_LOWERED``.  The
#: checkpoint-resume tests pin "completed fragments are never
#: re-lowered" against deltas of this counter (a checkpoint-fed
#: fragment does NOT bump it: its subtree is replaced by a host feed)
FRAGMENTS_LOWERED = 0


class MeshUnsupported(NotImplementedError):
    """Plan shape outside the mesh tier; caller falls back to operators."""


@dataclasses.dataclass
class MCol:
    """One column of a shard-local table inside the traced program."""

    values: object                 # traced array [cap]
    valid: object                  # traced bool array [cap] | None
    type: T.Type
    dictionary: Optional[Dictionary] = None


@dataclasses.dataclass
class MTable:
    """A shard-local row set: padded columns + live mask.

    ``est`` is the host-side estimate (upper bound where possible) of the
    TOTAL live rows across all shards — it sizes downstream capacities.
    ``compacted`` means live rows form a prefix on every shard.
    ``replicated`` means every shard holds the IDENTICAL rows (the result
    of a gather/broadcast, or anything derived from only-replicated
    inputs); exchanges must treat such a table as ONE copy, not as
    shard-distinct slices — gathering it again would multiply rows by the
    shard count (the Q15 scalar-subquery shape).
    """

    cols: List[MCol]
    live: object                   # traced bool [cap]
    cap: int
    est: int
    compacted: bool = False
    replicated: bool = False

    def pairs(self):
        return [(c.values, c.valid) for c in self.cols]

    @property
    def num_rows(self):
        import jax.numpy as jnp

        return self.live.sum().astype(jnp.int64)


def _check_supported(node: PlanNode) -> None:
    if isinstance(node, UnnestNode):
        raise MeshUnsupported(type(node).__name__)
    for _, t in node.columns:
        if t.is_nested:
            raise MeshUnsupported(f"nested type {t.display()}")
    if isinstance(node, AggregationNode):
        if any(a.distinct for a in node.aggregates):
            raise MeshUnsupported("distinct aggregate")
        for a in node.aggregates:
            for prim, _ in a.spec.components:
                if prim not in _MESH_PRIMS + ("sumsq", "sumln"):
                    raise MeshUnsupported(f"agg component {prim}")
    if isinstance(node, JoinNode):
        if node.kind not in ("inner", "left", "cross"):
            raise MeshUnsupported(f"{node.kind} join")
        if node.kind == "left" and node.residual is not None:
            raise MeshUnsupported("left-join residual")
    exprs: List[RowExpression] = []
    if isinstance(node, FilterNode):
        exprs.append(node.predicate)
    if isinstance(node, ProjectNode):
        exprs.extend(node.expressions)
    if isinstance(node, JoinNode) and node.residual is not None:
        exprs.append(node.residual)
    if exprs and needs_host_path(exprs):
        raise MeshUnsupported("host-path expression")
    for s in node.sources:
        _check_supported(s)


class MeshQueryRunner:
    """SQL in, rows out, over an n-device mesh (the distributed
    LocalQueryRunner: same front end, collective execution)."""

    _serial_counter = 0

    def __init__(self, registry: ConnectorRegistry, default_catalog: str,
                 n_devices: int = 8, config: EngineConfig = DEFAULT):
        from presto_tpu.parallel.mesh import make_mesh
        from presto_tpu.sql.planner import Metadata

        self.registry = registry
        self.metadata = Metadata(registry, default_catalog)
        self.config = config
        self.mesh = make_mesh(n_devices)
        self.nparts = n_devices
        # program-cache identity: compiled _MeshPrograms live in the
        # shared "mesh_program" kernelcache keyed (serial, sql), so the
        # registry's hit/miss/compile counters cover every runner
        MeshQueryRunner._serial_counter += 1
        self._serial = MeshQueryRunner._serial_counter
        # observability for the last successful execution: exchange-mode
        # counters per fragment boundary, per-shard stats read out of
        # the program, kernel-tier markers, and compile attribution (the
        # stats-rollup feed of the device-sharded exchange tier)
        self.last_run_info: Dict = {}

    @classmethod
    def tpch(cls, scale: float = 0.01, n_devices: int = 8,
             config: EngineConfig = DEFAULT) -> "MeshQueryRunner":
        from presto_tpu.connectors.tpcds import TpcdsConnector
        from presto_tpu.connectors.tpch import TpchConnector

        reg = ConnectorRegistry()
        reg.register("tpch", TpchConnector(scale=scale))
        reg.register("tpcds", TpcdsConnector(scale=scale))
        return cls(reg, "tpch", n_devices, config)

    @classmethod
    def tpcds(cls, scale: float = 0.003, n_devices: int = 8,
              config: EngineConfig = DEFAULT) -> "MeshQueryRunner":
        """TPC-DS default catalog — the BASELINE.md Q72/Q95 multi-chip
        configs on the SPMD mesh tier (shapes outside the mesh subset,
        e.g. Q95's COUNT(DISTINCT), raise MeshUnsupported and fall back
        to the operator tier like every other caller)."""
        from presto_tpu.connectors.tpcds import TpcdsConnector
        from presto_tpu.connectors.tpch import TpchConnector

        reg = ConnectorRegistry()
        reg.register("tpcds", TpcdsConnector(scale=scale))
        reg.register("tpch", TpchConnector(scale=scale))
        return cls(reg, "tpcds", n_devices, config)

    def plan_distributed(self, sql: str):
        from presto_tpu.sql.parser import parse_statement

        return self.plan_distributed_stmt(parse_statement(sql))

    def plan_distributed_stmt(self, stmt):
        from presto_tpu.sql import tree as t
        from presto_tpu.sql.optimizer import optimize
        from presto_tpu.sql.planner import Planner

        if not isinstance(stmt, (t.Query, t.SetOperation)):
            raise MeshUnsupported("only queries run on the mesh")
        logical = Planner(self.metadata).plan(stmt)
        return self.fragment_plan(optimize(logical, self.metadata))

    def fragment_plan(self, optimized):
        from presto_tpu.server.fragmenter import Fragmenter

        return Fragmenter(metadata=self.metadata,
                          config=self.config).fragment(optimized)

    def execute(self, sql: str):
        from presto_tpu.sql.parser import parse_statement

        return self.execute_stmt(parse_statement(sql), key=sql)

    def execute_stmt(self, stmt, key: Optional[str] = None):
        """Execute a parsed query; ``key`` caches the compiled program
        (falls back to the statement's repr — tree nodes are frozen
        dataclasses, so the repr is a stable structural key)."""
        return self._execute_planned(
            key if key is not None else repr(stmt),
            lambda: self.plan_distributed_stmt(stmt))

    def execute_plan(self, optimized, key: str):
        """Execute an ALREADY-optimized logical plan (LocalQueryRunner's
        whole-query path plans once and hands it over)."""
        return self._execute_planned(
            key, lambda: self.fragment_plan(optimized))

    def execute_dplan(self, dplan, key: str):
        """Execute an ALREADY-fragmented plan: the coordinator's
        device-sharded exchange tier hands its DistributedPlan over, so
        the collective tier and the HTTP tier run the IDENTICAL fragment
        DAG — only the boundary transport differs (in-program collective
        vs PartitionedOutput -> wire pages -> ExchangeOperator)."""
        return self._execute_planned(key, lambda: dplan)

    def execute_dplan_checkpointed(self, dplan, key: str, *,
                                   completed=None, on_checkpoint=None,
                                   fault_hook=None):
        """Execute a fragmented plan as a SEQUENCE of per-fragment SPMD
        programs (``mesh_checkpoint_boundaries``): fragments run in
        topological (producers-first) order; each group's root output is
        read back to the host — the boundary checkpoint — and handed to
        ``on_checkpoint(fid, batch)`` (the coordinator spools it); later
        groups are fed from the checkpointed batches instead of
        re-lowering their producers.  ``completed`` maps fragment id ->
        host Batch for checkpoints that already exist: on resume, those
        groups are SKIPPED entirely (zero re-execution, zero
        re-lowering).  ``fault_hook(fid)`` fires before each group, the
        chaos-injection seam.  Checkpoint-group programs are never
        program-cached: restartability is bought with per-group
        dispatch, so repeat queries should run the whole-program path."""
        from presto_tpu.localrunner import QueryResult

        completed = {} if completed is None else completed
        for frag in dplan.fragments:
            _check_supported(frag.root)
        all_info: List[Dict] = []
        lowered: List[int] = []
        for fid in self._group_order(dplan):
            if fid != dplan.root_fragment_id and fid in completed:
                continue
            if fault_hook is not None:
                fault_hook(fid)
            prog = None
            batch = None
            for attempt in range(4):
                prog = _MeshProgram(self, dplan,
                                    cap_scale=1 << attempt,
                                    prepared=prog, root_fid=fid,
                                    ckpt=completed)
                batch, overflowed = prog.run()
                if not overflowed:
                    break
                batch = None
            if batch is None:
                raise MeshUnsupported(
                    f"mesh execution did not converge on fragment {fid}"
                    + (f" ({', '.join(prog.overflow_labels)})"
                       if getattr(prog, 'overflow_labels', None)
                       else ""))
            all_info.append(dict(
                prog.run_info(), compile_ns=prog.compile_ns,
                build_spans=dict(prog.build_spans)))
            lowered.extend(prog.lowered_fids)
            if fid == dplan.root_fragment_id:
                self.last_run_info = _merge_run_info(
                    all_info,
                    checkpoints=sorted(completed),
                    lowered=sorted(set(lowered)))
                return QueryResult(dplan.column_names,
                                   dplan.column_types,
                                   batch.to_pylist())
            completed[fid] = batch
            if on_checkpoint is not None:
                on_checkpoint(fid, batch)
        raise MeshUnsupported("plan has no reachable root fragment")

    @staticmethod
    def _group_order(dplan) -> List[int]:
        """Checkpoint-group schedule: DFS postorder from the root, so
        every fragment runs after all the fragments it consumes."""
        order: List[int] = []
        seen = set()

        def visit(fid: int) -> None:
            if fid in seen:
                return
            seen.add(fid)
            stack = [dplan.fragments[fid].root]
            child: List[int] = []
            while stack:
                node = stack.pop()
                fids = getattr(node, "fragment_ids", None)
                if fids:
                    child.extend(fids)
                stack.extend(node.sources)
            for c in sorted(child):
                visit(c)
            order.append(fid)

        visit(dplan.root_fragment_id)
        return order

    def _execute_planned(self, sql: str, make_dplan):
        from presto_tpu.localrunner import QueryResult

        cache_key = (self._serial, sql)
        cached = _kc.cache_get(_PROGRAM_CACHE, cache_key)
        if cached is not None:
            # repeat query: the compiled SPMD program and device-resident
            # scan inputs are reused — one dispatch per execution (the
            # kernel-cache policy applied at whole-query granularity).
            # A cross-query cache hit reports compile_ns=0: the compile
            # was paid (and attributed) by the run that built it.
            batch, overflowed = cached.run()
            if not overflowed:
                dplan = cached.dplan
                self.last_run_info = dict(cached.run_info(),
                                          compile_ns=0,
                                          program_cached=True)
                return QueryResult(dplan.column_names, dplan.column_types,
                                   batch.to_pylist())
            _kc.cache_pop(_PROGRAM_CACHE, cache_key)
        dplan = make_dplan()
        for frag in dplan.fragments:
            _check_supported(frag.root)
        last_err = None
        prog = None
        for attempt in range(4):
            prog = _MeshProgram(self, dplan, cap_scale=1 << attempt,
                                prepared=prog)
            batch, overflowed = prog.run()
            if not overflowed:
                if prog.cacheable:
                    _kc.cache_put(_PROGRAM_CACHE, cache_key, prog)
                self.last_run_info = dict(
                    prog.run_info(), compile_ns=prog.compile_ns,
                    program_cached=False,
                    build_spans=dict(prog.build_spans))
                return QueryResult(dplan.column_names, dplan.column_types,
                                   batch.to_pylist())
            last_err = f"overflow at cap_scale={1 << attempt}"
        # the query expands beyond every capacity bucket this tier will
        # try: report it as unsupported so callers take the operator-tier
        # fallback path instead of failing the query
        raise MeshUnsupported(
            f"mesh execution did not converge: {last_err}"
            + (f" ({', '.join(prog.overflow_labels)})"
               if getattr(prog, 'overflow_labels', None) else ""))


def _merge_run_info(infos: List[Dict], checkpoints: List[int],
                    lowered: List[int]) -> Dict:
    """Fold per-checkpoint-group run_info dicts into ONE whole-query
    view shaped exactly like a whole-program run_info, plus the
    checkpoint accounting (group count, checkpointed fragment ids,
    fragments actually lowered) the resume tests and the EXPLAIN
    ANALYZE footer consume."""
    merged: Dict = {
        "exchange_modes": {}, "boundaries": [], "kernel_tiers": [],
        "nparts": infos[-1]["nparts"] if infos else 0,
        "cap_scale": max((i["cap_scale"] for i in infos), default=1),
        "per_shard": {"fragments": {}, "peak_live_bytes": []},
        "checkpoint_groups": len(infos),
        "checkpoints": list(checkpoints),
        "fragments_lowered": list(lowered),
        "compile_ns": sum(i.get("compile_ns", 0) for i in infos),
        "program_cached": False,
    }
    spans: Dict[str, Tuple[float, float]] = {}
    peak: Optional[List[int]] = None
    for info in infos:
        for k, v in info["exchange_modes"].items():
            merged["exchange_modes"][k] = \
                merged["exchange_modes"].get(k, 0) + v
        merged["boundaries"].extend(info["boundaries"])
        merged["kernel_tiers"].extend(info["kernel_tiers"])
        merged["per_shard"]["fragments"].update(
            info["per_shard"]["fragments"])
        p = info["per_shard"]["peak_live_bytes"]
        peak = list(p) if peak is None else [max(a, b)
                                            for a, b in zip(peak, p)]
        for k, (s, e) in (info.get("build_spans") or {}).items():
            cur = spans.get(k)
            spans[k] = (s, e) if cur is None else (min(cur[0], s),
                                                  max(cur[1], e))
    merged["per_shard"]["peak_live_bytes"] = peak or []
    merged["build_spans"] = spans
    return merged


class _MeshProgram:
    """One capacity-bucket attempt: host scan prep + traced lowering.

    ``root_fid``/``ckpt`` carve one CHECKPOINT GROUP out of the DAG:
    the program lowers only the subtree reachable from ``root_fid``,
    replacing every checkpointed producer fragment in ``ckpt`` (fid ->
    host Batch of that fragment's global output rows) with a sharded
    host feed staged exactly like a base-table scan.  Defaults lower
    the whole DAG from the plan root — byte-identical to PR 11."""

    def __init__(self, runner: MeshQueryRunner, dplan, cap_scale: int,
                 prepared: Optional["_MeshProgram"] = None,
                 root_fid: Optional[int] = None,
                 ckpt: Optional[Dict[int, Batch]] = None):
        self.runner = runner
        self.dplan = dplan
        self.cap_scale = cap_scale
        self.nparts = runner.nparts
        self.config = runner.config
        self.root_fid = (dplan.root_fragment_id if root_fid is None
                         else root_fid)
        self.ckpt = ckpt if ckpt is not None else {}
        # fragments THIS program actually lowered (trace-time), the
        # per-program never-re-lowered accounting
        self.lowered_fids: List[int] = []
        self._root_replicated = False
        self._jitted = None
        self._args = None
        # trace-time observability, kept across cached re-runs: one
        # (fragment id, collective kind) entry per fragment boundary and
        # one (operator label, tier) marker per hot-loop lowering
        self.exchange_log: List[Tuple[int, str]] = []
        self.kernel_tiers: List[Tuple[str, str]] = []
        # compile attribution: XLA-compile wall (timed_first_call over
        # the AOT compile) + the lower/compile wall-clock windows the
        # coordinator turns into span-tree phases; per-shard telemetry
        # values read back from the LAST run's stats output
        self.compile_ns = 0
        self.build_spans: Dict[str, Tuple[float, float]] = {}
        self._last_shard_stats: List[Tuple[tuple, List[int]]] = []
        # a retry shares the prepared scans, so it must inherit their
        # mutability verdict (scan prep is the only place it is learned)
        self.cacheable = prepared.cacheable if prepared is not None \
            else True
        if prepared is not None:
            # overflow retry: only capacities change — reuse the loaded,
            # sharded scan inputs instead of re-reading every base table
            # (and the staged checkpoint feeds alongside them)
            self.inputs = prepared.inputs
            self.scan_meta = prepared.scan_meta
            self.ckpt_meta = prepared.ckpt_meta
        else:
            self.inputs: List[np.ndarray] = []
            self.scan_meta: Dict[int, dict] = {}
            self.ckpt_meta: Dict[int, dict] = {}
            self._prepare_scans()

    # ---------------- host phase ----------------
    def _prepare_scans(self) -> None:
        if self.root_fid == self.dplan.root_fragment_id \
                and not self.ckpt:
            frags = list(self.dplan.fragments)
        else:
            # checkpoint group: stage scans only for the fragments this
            # group lowers, and a host feed per checkpointed producer
            needed, feeds = self._needed_fragments()
            frags = [self.dplan.fragments[f] for f in needed]
            for fid in sorted(feeds):
                self._prepare_checkpoint_feed(fid, self.ckpt[fid])
        for frag in frags:
            stack = [frag.root]
            while stack:
                node = stack.pop()
                if isinstance(node, TableScanNode):
                    self._prepare_scan(node, frag)
                stack.extend(node.sources)

    def _needed_fragments(self) -> Tuple[List[int], List[int]]:
        """Fragment ids this group lowers (reachable from ``root_fid``
        WITHOUT descending through checkpointed producers) and the
        checkpointed fragment ids it consumes as host feeds."""
        needed: List[int] = []
        feeds: List[int] = []
        stack = [self.root_fid]
        seen = set()
        while stack:
            fid = stack.pop()
            if fid in seen:
                continue
            seen.add(fid)
            if fid != self.root_fid and fid in self.ckpt:
                feeds.append(fid)
                continue
            needed.append(fid)
            nstack = [self.dplan.fragments[fid].root]
            while nstack:
                node = nstack.pop()
                fids = getattr(node, "fragment_ids", None)
                if fids:
                    stack.extend(fids)
                nstack.extend(node.sources)
        return needed, feeds

    def _prepare_checkpoint_feed(self, fid: int, batch: Batch) -> None:
        """Stage a checkpointed fragment's GLOBAL output rows as sharded
        program inputs, exactly like a base-table scan: contiguous
        split across shards into padded [P, cap] grids.  The consumer's
        boundary collective rehashes/gathers the feed, so the
        contiguous placement is semantically neutral — the checkpoint
        captured the fragment root's output BEFORE the exchange."""
        P = self.nparts
        b = batch.to_numpy()
        n = b.num_rows
        base, rem = divmod(n, P)
        counts = np.asarray([base + (i < rem) for i in range(P)],
                            np.int64)
        cap = next_bucket(int(counts.max()), minimum=8)
        offsets = np.concatenate([[0], np.cumsum(counts)])
        slots = []
        col_meta = []
        for col in b.columns:
            vals = np.asarray(col.values)[:n]
            g = np.zeros((P, cap), vals.dtype)
            for i in range(P):
                g[i, : counts[i]] = vals[offsets[i]:offsets[i + 1]]
            vslot = len(self.inputs)
            self.inputs.append(g.reshape(P * cap))
            gslot = None
            if col.valid is not None:
                va = np.asarray(col.valid)[:n]
                gv = np.zeros((P, cap), bool)
                for i in range(P):
                    gv[i, : counts[i]] = va[offsets[i]:offsets[i + 1]]
                gslot = len(self.inputs)
                self.inputs.append(gv.reshape(P * cap))
            slots.append((vslot, gslot))
            col_meta.append((col.type, col.dictionary))
        cslot = len(self.inputs)
        self.inputs.append(counts)
        self.ckpt_meta[fid] = {
            "slots": slots, "counts": cslot, "cap": cap, "total": n,
            "meta": col_meta,
        }

    def _prepare_scan(self, node: TableScanNode, frag) -> None:
        P = self.nparts
        conn = self.runner.registry.get(node.catalog)
        if not getattr(conn, "immutable_data", False):
            # the compiled program embeds this scan's rows; a mutable
            # table (memory connector INSERTs...) would serve stale data
            # from the cache — execute, but do not cache
            self.cacheable = False
        handle = conn.get_table(node.table)
        splits = conn.get_splits(handle, 1)
        batches = []
        for split in splits:
            batches.extend(conn.page_source(split, list(node.column_names),
                                            1 << 24))
        if batches:
            b = (concat_batches(batches) if len(batches) > 1
                 else batches[0]).to_numpy()
        else:
            b = batch_from_pylist(node.types, [])
        n = b.num_rows
        single = frag.partitioning == "single"
        if single:
            counts = np.zeros(P, np.int64)
            counts[0] = n
        else:
            base, rem = divmod(n, P)
            counts = np.asarray([base + (i < rem) for i in range(P)],
                                np.int64)
        cap = next_bucket(int(counts.max()), minimum=8)
        slots = []
        col_meta = []
        offsets = np.concatenate([[0], np.cumsum(counts)])
        for ci, col in enumerate(b.columns):
            vals = np.asarray(col.values)[:n]
            g = np.zeros((P, cap), vals.dtype)
            for i in range(P):
                g[i, : counts[i]] = vals[offsets[i]:offsets[i + 1]]
            vslot = len(self.inputs)
            self.inputs.append(g.reshape(P * cap))
            gslot = None
            if col.valid is not None:
                va = np.asarray(col.valid)[:n]
                gv = np.zeros((P, cap), bool)
                for i in range(P):
                    gv[i, : counts[i]] = va[offsets[i]:offsets[i + 1]]
                gslot = len(self.inputs)
                self.inputs.append(gv.reshape(P * cap))
            slots.append((vslot, gslot))
            col_meta.append((col.type, col.dictionary))
        cslot = len(self.inputs)
        self.inputs.append(counts)
        self.scan_meta[id(node)] = {
            "slots": slots, "counts": cslot, "cap": cap, "total": n,
            "meta": col_meta,
        }

    # ---------------- run ----------------
    def run(self) -> Tuple[Batch, bool]:
        import jax
        from jax.sharding import PartitionSpec as PS

        from presto_tpu.parallel.mesh import AXIS, row_sharding

        root_frag = self.dplan.fragments[self.root_fid]
        ncols = len(root_frag.root.columns)
        if self._jitted is None:
            # _out_meta/_flag_labels are trace-time side effects; cached
            # re-runs skip the trace and must keep the recorded values
            self._out_meta: List[Tuple[T.Type, Optional[Dictionary]]] = []

        def program(*inputs):
            import jax.numpy as jnp

            self._traced = inputs
            self._cache: Dict[int, MTable] = {}
            self._overflow: List[object] = []
            self._errors: List[object] = []
            self.exchange_log = []
            self.kernel_tiers = []
            # per-shard telemetry accumulated during lowering: (key,
            # traced int64 scalar) pairs that become ONE stats vector
            # output — the program's own StageStats feed
            self._shard_stats: List[Tuple[tuple, object]] = []
            self._peak_live = jnp.zeros((), jnp.int64)
            table = self._lower_fragment(self.root_fid)
            self._root_replicated = table.replicated
            self._out_meta = [(c.type, c.dictionary) for c in table.cols]
            outs = []
            for c in table.cols:
                outs.append(c.values)
                outs.append(c.valid if c.valid is not None
                            else jnp.ones(table.cap, bool))
            of = jnp.zeros((), bool)
            flags = []
            for _, f in self._overflow:
                of = of | f
                flags.append(f)
            self._flag_labels = [lbl for lbl, _ in self._overflow]
            err = jnp.zeros((), bool)
            for f in self._errors:
                err = err | f
            self._shard_stats.append(
                (("program", "peak_live_bytes"), self._peak_live))
            self._stat_keys = [k for k, _ in self._shard_stats]
            stats = jnp.stack([jnp.asarray(v).astype(jnp.int64).reshape(())
                               for _, v in self._shard_stats])
            return (tuple(outs) + (table.live, of.reshape(1),
                                   err.reshape(1),
                                   jnp.stack(flags).reshape(-1)
                                   if flags else jnp.zeros(0, bool),
                                   stats))

        n_out = 2 * ncols + 5
        if self._jitted is None:
            import time as _time

            from presto_tpu.exec.context import OperatorStats
            from presto_tpu.kernelcache import timed_first_call

            mapped = jax.shard_map(
                program, mesh=self.runner.mesh,
                in_specs=tuple(PS(AXIS) for _ in self.inputs),
                out_specs=tuple(PS(AXIS) for _ in range(n_out)),
                check_vma=False)
            self._args = [
                jax.device_put(a, row_sharding(self.runner.mesh, 1))
                for a in self.inputs]
            # AOT-compile and keep the loaded executable: the plain
            # jit dispatch path can lose the trace-time constant buffers
            # when several whole-query programs coexist in one process
            # (observed as "supplied N buffers but expected N+consts");
            # the AOT executable binds its constants explicitly.  The
            # trace+lower and XLA-compile walls are split so the span
            # tree can attribute them separately; compile wall is
            # attributed to the shared "mesh_program" cache through
            # timed_first_call (the CacheStatsMBean role).
            t0 = _time.time()
            lowered = jax.jit(mapped).lower(*self._args)
            t1 = _time.time()
            cstats = OperatorStats(operator="mesh_program")
            self._jitted = timed_first_call(
                lowered.compile, cstats, _PROGRAM_CACHE)()
            t2 = _time.time()
            self.compile_ns += cstats.jit_compile_ns
            self.build_spans = {"lower": (t0, t1), "compile": (t1, t2)}
        out = self._jitted(*self._args)
        # Read only the control outputs eagerly — on a remote-attached
        # TPU every host transfer costs a tunnel round trip, and the
        # content arrays are full static capacity regardless of how few
        # rows are live.
        of = bool(np.asarray(out[-4]).any())
        if of:
            flags = np.asarray(out[-2]).reshape(self.nparts, -1)
            self.overflow_labels = [
                lbl for i, lbl in enumerate(self._flag_labels)
                if flags[:, i].any()]
            return Batch((), 0), True
        if bool(np.asarray(out[-3]).any()):
            raise ValueError(
                "scalar subquery returned more than one row")
        self._read_shard_stats(out[-1])
        live_g = np.asarray(out[-5])
        cap = live_g.shape[0] // self.nparts
        if self.root_fid != self.dplan.root_fragment_id \
                and not self._root_replicated:
            # checkpoint-group readback of a DISTRIBUTED root: the
            # boundary checkpoint is the fragment's GLOBAL live multiset
            # (pre-exchange), so concatenate every shard's live rows.
            # The plan root stays on the shard-0 fast path below — a
            # 'single'-partitioned root gathers to shard 0 in-program.
            return self._gather_all_shards(out, live_g, cap), False
        live = live_g[:cap]
        n_live = int(live.sum())
        ncols = len(self._out_meta)
        # One extra device dispatch compacts live rows to a prefix bucket
        # and stacks same-dtype outputs, so the host reads a handful of
        # right-sized arrays instead of 2*ncols capacity-sized ones (the
        # tunnel charges a round trip per array AND bytes).
        bucket = min(next_bucket(max(n_live, 1), minimum=8), cap)
        host = self._sliced_content(out, cap, bucket, ncols)
        cols = []
        for i, (typ, d) in enumerate(self._out_meta):
            vals = host[2 * i][:n_live]
            valid = host[2 * i + 1][:n_live]
            cols.append(Column(typ, vals,
                               None if valid.all() else valid, d))
        return Batch(tuple(cols), n_live), False

    def _gather_all_shards(self, out, live_g: np.ndarray,
                           cap: int) -> Batch:
        """Host-side concat of every shard's live rows, shard order —
        the checkpoint capture path.  Plain O(cap) transfers: checkpoint
        groups are dispatched once per boundary, not per repeat query,
        so the slicer machinery is not worth specializing here."""
        P = self.nparts
        live_pg = live_g.reshape(P, cap).astype(bool)
        n_live = int(live_pg.sum())
        cols = []
        for i, (typ, d) in enumerate(self._out_meta):
            vals_g = np.asarray(out[2 * i]).reshape(P, cap)
            valid_g = np.asarray(out[2 * i + 1]).reshape(P, cap)
            vals = np.concatenate([vals_g[p][live_pg[p]]
                                   for p in range(P)])
            valid = np.concatenate([valid_g[p][live_pg[p]]
                                    for p in range(P)])
            cols.append(Column(typ, vals,
                               None if valid.all() else valid, d))
        return Batch(tuple(cols), n_live)

    def _sliced_content(self, out, cap: int, bucket: int, ncols: int):
        """Device-side stable compaction of live rows + slice to the
        ``bucket`` prefix; transfers O(live) bytes instead of O(cap)."""
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_slicers"):
            self._slicers = {}
        arrays = list(out[:2 * ncols])
        # group same-dtype outputs into one stacked transfer each: the
        # tunnel charges a round trip PER ARRAY, which dominates once the
        # payloads are small
        groups: Dict[object, List[int]] = {}
        for i, a in enumerate(arrays):
            groups.setdefault(np.dtype(a.dtype), []).append(i)
        layout = tuple(sorted((str(k), tuple(v)) for k, v in groups.items()))
        fn = self._slicers.get((bucket, layout))
        if fn is None:
            from presto_tpu.ops.radix import stable_partition_perm

            def slicer(arrs, live_full):
                perm = stable_partition_perm(~live_full[:cap])[:bucket]
                return tuple(jnp.stack([arrs[i][:cap][perm] for i in idxs])
                             for _, idxs in layout)

            fn = jax.jit(slicer)
            self._slicers[(bucket, layout)] = fn
        stacked = [np.asarray(a) for a in fn(tuple(arrays), out[-5])]
        host: List[Optional[np.ndarray]] = [None] * len(arrays)
        for (_, idxs), mat in zip(layout, stacked):
            for row, i in enumerate(idxs):
                host[i] = mat[row]
        return host

    def _read_shard_stats(self, stats_out) -> None:
        """Parse the program's stats vector output ([P*S] -> [P, S])
        into per-key per-shard int lists; same-key entries (several
        scans in one fragment) sum."""
        raw = np.asarray(stats_out).reshape(self.nparts, -1)
        folded: Dict[tuple, np.ndarray] = {}
        order: List[tuple] = []
        for i, key in enumerate(self._stat_keys):
            if key not in folded:
                folded[key] = np.zeros(self.nparts, np.int64)
                order.append(key)
            folded[key] += raw[:, i]
        self._last_shard_stats = [(k, [int(v) for v in folded[k]])
                                  for k in order]

    def _note_stat(self, key: tuple, value) -> None:
        self._shard_stats.append((key, value))

    def run_info(self) -> Dict:
        """Exchange-mode + kernel-tier counters and the per-shard stats
        read back from the LAST run, for the stats rollup (structure
        recorded at trace time; cached re-runs re-read the same compiled
        program's outputs)."""
        modes: Dict[str, int] = {}
        for _fid, kind in self.exchange_log:
            modes[kind] = modes.get(kind, 0) + 1
        stats = dict(self._last_shard_stats)
        fragments: Dict[int, Dict[str, List[int]]] = {}
        boundaries = []
        peak = stats.get(("program", "peak_live_bytes"),
                         [0] * self.nparts)
        for key, vals in self._last_shard_stats:
            if key[0] == "fragment":
                fragments.setdefault(key[1], {})[key[2]] = vals
        for seq, (fid, kind) in enumerate(self.exchange_log):
            boundaries.append({
                "fragment": fid, "kind": kind,
                "rows": stats.get(("boundary", seq, fid, kind, "rows"),
                                  [0] * self.nparts),
                "bytes": stats.get(("boundary", seq, fid, kind, "bytes"),
                                   [0] * self.nparts),
            })
        return {
            "exchange_modes": modes,
            "boundaries": boundaries,
            "kernel_tiers": [f"{label}:{tier}"
                             for label, tier in self.kernel_tiers],
            "nparts": self.nparts,
            "cap_scale": self.cap_scale,
            "per_shard": {
                "fragments": {
                    fid: {"input_rows": d.get("input_rows",
                                              [0] * self.nparts),
                          "output_rows": d.get("output_rows",
                                               [0] * self.nparts)}
                    for fid, d in sorted(fragments.items())},
                "peak_live_bytes": peak,
            },
        }

    # ---------------- traced lowering ----------------
    def _lower_fragment(self, fid: int) -> MTable:
        if fid in self._cache:
            return self._cache[fid]
        if fid != self.root_fid and fid in self.ckpt:
            table = self._ckpt_table(fid)
            self._cache[fid] = table
            return table
        global FRAGMENTS_LOWERED
        FRAGMENTS_LOWERED += 1
        if fid not in self.lowered_fids:
            self.lowered_fids.append(fid)
        frag = self.dplan.fragments[fid]
        prev = getattr(self, "_cur_part", None)
        prev_fid = getattr(self, "_cur_fid", None)
        self._cur_part = frag.partitioning
        self._cur_fid = fid
        try:
            table = self._lower(frag.root)
        finally:
            self._cur_part = prev
            self._cur_fid = prev_fid
        # per-shard fragment output rows: live count of the fragment
        # root (the TaskStats.output_rows feed of the synthetic rollup)
        self._note_stat(("fragment", fid, "output_rows"), table.num_rows)
        self._cache[fid] = table
        return table

    def _exchange(self, fid: int) -> MTable:
        """Apply the fragment-boundary collective (the PartitionedOutput/
        Broadcast/TaskOutput -> ExchangeOperator hop as an in-program ICI
        collective).  The collective is chosen like the HTTP tier routes
        partitions: a 'single'-partitioned consumer has ONE task pulling
        every partition, so hash-partitioned producer output degenerates
        to a gather; multi-task consumers see the producer's routing."""
        import jax.numpy as jnp

        from presto_tpu.parallel.exchange import (
            broadcast_rows, repartition, route_by_key,
        )
        from presto_tpu.parallel.mesh import AXIS

        import jax

        frag = self.dplan.fragments[fid]
        consumer_part = self._cur_part
        table = self._lower_fragment(fid)
        self._cur_part = consumer_part
        kind, channels = frag.output_partitioning
        if consumer_part == "single":
            # root/gather consumer: all partitions flow to the one task
            kind = "single"
        if table.replicated:
            if kind in ("broadcast", "single"):
                # already the identical union on every shard — a gather
                # here would multiply rows by the shard count (the
                # boundary still counts: it lowered to an identity,
                # moving zero bytes)
                self._note_boundary(fid, kind, table.num_rows, 0)
                return table
            # hash-split of a replicated table: only ONE copy may enter
            # the exchange, so mask all but shard 0's
            on_first = jax.lax.axis_index(AXIS) == 0
            table = MTable(table.cols, table.live & on_first, table.cap,
                           table.est, compacted=False)
        if kind in ("hash", "arbitrary") \
                and self.config.partitioned_join_build and self.nparts > 1:
            # P8 sharded sizing: a key-routed receive buffer holds this
            # shard's PARTITION of the rows, not the worst-case total —
            # 2x the even share for skew head room, cap_scale doubling
            # on overflow retry.  This is what makes per-shard state
            # (and the build table sized from it) scale with 1/P, so a
            # build exceeding one device's HBM becomes legal.  Knob off
            # restores the PR 10 worst-case-total sizing exactly.
            out_cap = next_bucket(
                max(8, (2 * self.cap_scale * table.est) // self.nparts))
        else:
            out_cap = next_bucket(table.est, minimum=8)

        def col_arrays(t: MTable):
            out = []
            for c in t.cols:
                out.append(c.values)
                out.append(c.valid if c.valid is not None
                           else jnp.ones(t.cap, bool))
            return out

        if kind in ("hash", "arbitrary"):
            arrays = col_arrays(table)
            if kind == "hash":
                triples = [self._hash_triple(table.cols[ch])
                           for ch in channels]
                recv, n_recv, of = route_by_key(
                    arrays, table.live, triples,
                    slot_cap=min(table.cap, out_cap), out_cap=out_cap,
                    axis_name=AXIS)
            else:
                # P3 round-robin: rotate rows across shards for balance
                # (no key semantics downstream)
                dest = ((jnp.arange(table.cap)
                         + jax.lax.axis_index(AXIS))
                        % self.nparts).astype(jnp.int32)
                recv, n_recv, of = repartition(
                    arrays, table.live, dest,
                    slot_cap=min(table.cap, out_cap), out_cap=out_cap,
                    axis_name=AXIS)
        elif kind in ("broadcast", "single"):
            ct = _compact(table)
            recv, n_recv, of = broadcast_rows(col_arrays(ct), ct.num_rows,
                                              out_cap, AXIS)
        else:
            raise MeshUnsupported(f"output partitioning {kind}")
        self._overflow.append((f'exchange f{fid} {kind}', of))
        # per-shard boundary telemetry: rows/bytes this shard RECEIVED
        # through the collective (raw device arrays, so bytes = rows x
        # static row width — no serde framing), plus the mid-program
        # progress beacon when enabled
        from presto_tpu.parallel.exchange import row_width_bytes

        self._note_boundary(fid, kind, n_recv,
                            n_recv * row_width_bytes(recv))
        cols = []
        for i, c in enumerate(table.cols):
            cols.append(MCol(recv[2 * i], recv[2 * i + 1], c.type,
                             c.dictionary))
        live = jnp.arange(out_cap) < n_recv
        return MTable(cols, live, out_cap, table.est, compacted=True,
                      replicated=kind in ("broadcast", "single"))

    def _note_boundary(self, fid: int, kind: str, rows, bytes_) -> None:
        """Record one fragment boundary: exchange-log entry, per-shard
        rows/bytes stats keyed by boundary sequence (a fragment feeding
        two consumers crosses two boundaries), and — when
        ``mesh_progress_beacons`` is on — a ``jax.debug.callback``
        beacon reporting (fragment, shard, rows) to the host collector
        mid-program.  Beacons off traces NO callback: the program is
        byte-identical to the PR 11 lowering."""
        seq = len(self.exchange_log)
        self.exchange_log.append((fid, kind))
        self._note_stat(("boundary", seq, fid, kind, "rows"), rows)
        self._note_stat(("boundary", seq, fid, kind, "bytes"), bytes_)
        # beacons ride only the device-exchange tier: the local
        # whole_query_execution tier traces through this module too and
        # must stay callback-free (its progress plane is the operator
        # tier's — and a no-op host callback is still a host sync)
        if self.config.mesh_progress_beacons \
                and self.config.mesh_device_exchange:
            import jax
            import jax.numpy as jnp

            from presto_tpu.parallel import beacons
            from presto_tpu.parallel.mesh import AXIS

            jax.debug.callback(
                beacons.emit, jnp.int32(fid),
                jax.lax.axis_index(AXIS).astype(jnp.int32),
                jnp.asarray(rows).astype(jnp.int64), ordered=False)

    def _hash_triple(self, c: MCol):
        """(values, valid, type) for exchange hashing — the SAME per-entry
        value hash the HTTP data plane and partitioned spill use, so every
        tier routes equal keys to the same partition."""
        from presto_tpu.ops.hashing import value_hash_triple

        return value_hash_triple(c)

    def _lower(self, node: PlanNode) -> MTable:
        table = self._lower_node(node)
        # peak live-intermediate estimate: the largest live-rows x
        # row-width of any lowered table on this shard — the mesh
        # tier's peak_memory_bytes analogue (an estimate: padding and
        # kernel scratch are excluded; capacities are static and the
        # point is the LIVE working set)
        import jax.numpy as jnp

        from presto_tpu.parallel.exchange import row_width_bytes

        width = row_width_bytes(
            [c.values for c in table.cols]) + len(table.cols)
        self._peak_live = jnp.maximum(
            self._peak_live, table.num_rows * jnp.int64(max(width, 1)))
        return table

    def _lower_node(self, node: PlanNode) -> MTable:
        if isinstance(node, TableScanNode):
            return self._lower_scan(node)
        if isinstance(node, RemoteSourceNode):
            tables = [self._exchange(fid) for fid in node.fragment_ids]
            return tables[0] if len(tables) == 1 else _concat(tables)
        if isinstance(node, RemoteMergeNode):
            tables = [self._exchange(fid) for fid in node.fragment_ids]
            t0 = tables[0] if len(tables) == 1 else _concat(tables)
            t0 = self._sort(t0, node.sort_keys)
            if node.limit is not None:
                t0 = _limit(t0, node.limit, self.nparts)
            return t0
        if isinstance(node, ValuesNode):
            return self._lower_values(node)
        if isinstance(node, FilterNode):
            return self._lower_filter(node)
        if isinstance(node, ProjectNode):
            return self._lower_project(node)
        if isinstance(node, AggregationNode):
            return self._lower_agg(node)
        if isinstance(node, JoinNode):
            return self._lower_join(node)
        if isinstance(node, SemiJoinNode):
            return self._lower_semijoin(node)
        if isinstance(node, SortNode):
            return self._sort(self._lower(node.source), node.sort_keys)
        if isinstance(node, LimitNode):
            return _limit(self._lower(node.source), node.count,
                          self.nparts)
        if isinstance(node, UnionNode):
            return _concat([self._lower(s) for s in node.inputs])
        if isinstance(node, EnforceSingleRowNode):
            return self._lower_single_row(node)
        if isinstance(node, WindowNode):
            return self._lower_window(node)
        raise MeshUnsupported(f"mesh lowering for {type(node).__name__}")

    def _lower_window(self, node: WindowNode) -> MTable:
        """Window functions as segmented scans over a partition-sorted
        shard (WindowOperator.java:61 role; kernels in ops/window.py,
        shared with the operator tier via eval_window_function).

        Window fragments are single-partitioned (the fragmenter's
        _parallel_safe veto), so a sharded input is first replicated —
        every shard then holds whole partitions and computes identical
        results, which is exactly the 'single' fragment contract."""
        import jax.numpy as jnp

        from presto_tpu.exec.windowop import eval_window_function
        from presto_tpu.ops import window as W

        src = self._lower(node.source)
        if not src.replicated and self.nparts > 1:
            from presto_tpu.parallel.exchange import broadcast_rows
            from presto_tpu.parallel.mesh import AXIS

            ct = _compact(src)
            out_cap = next_bucket(self.nparts * src.est, minimum=8)
            arrays = []
            for c in ct.cols:
                arrays.append(c.values)
                arrays.append(c.valid if c.valid is not None
                              else jnp.ones(ct.cap, bool))
            recv, n_recv, of = broadcast_rows(arrays, ct.num_rows,
                                              out_cap, AXIS)
            self._overflow.append(('window gather', of))
            cols = [MCol(recv[2 * i], recv[2 * i + 1], c.type, c.dictionary)
                    for i, c in enumerate(ct.cols)]
            src = MTable(cols, jnp.arange(out_cap) < n_recv, out_cap,
                         self.nparts * src.est, compacted=True,
                         replicated=True)
        table = _compact(src)
        cap = table.cap
        n = table.num_rows

        sort_keys = [(ch, True, False) for ch in node.partition_channels]
        sort_keys += [(ch, asc, bool(nf)) for ch, asc, nf in node.order_keys]
        if sort_keys:
            table = self._sort(table, sort_keys)
        live = jnp.arange(cap) < n

        def eq_prev(ch: int):
            c = table.cols[ch]
            v = c.values
            same = jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), v[1:] == v[:-1]])
            if c.valid is not None:
                g = c.valid
                both_null = jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), (~g[1:]) & (~g[:-1])])
                both_ok = jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), g[1:] & g[:-1]])
                same = both_null | (both_ok & same)
            return same

        part_eq = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                   live[1:] == live[:-1]])
        for ch in node.partition_channels:
            part_eq = part_eq & eq_prev(ch)
        seg = W.segment_ids(part_eq)
        peer_eq = part_eq
        for ch, _, _ in node.order_keys:
            peer_eq = peer_eq & eq_prev(ch)
        peer = W.segment_ids(peer_eq)

        out_cols = list(table.cols)
        for fn in node.functions:
            rt, vals, ok, d = eval_window_function(fn, table.cols, seg,
                                                   peer)
            out_cols.append(MCol(vals, ok, rt, d))
        return MTable(out_cols, live, cap, table.est, compacted=True,
                      replicated=table.replicated)

    def _ckpt_table(self, fid: int) -> MTable:
        """A checkpointed fragment as a shard-local table: the staged
        host feed read back through the traced inputs, mirroring
        ``_lower_scan`` (``counts[0]`` inside shard_map is the LOCAL
        shard's count).  NOT replicated — the feed is one global copy
        split across shards, so the consumer's collective applies."""
        import jax.numpy as jnp

        meta = self.ckpt_meta[fid]
        cap = meta["cap"]
        counts = self._traced[meta["counts"]]
        cols = []
        for (vslot, gslot), (typ, d) in zip(meta["slots"], meta["meta"]):
            cols.append(MCol(self._traced[vslot],
                             self._traced[gslot] if gslot is not None
                             else None, typ, d))
        self.kernel_tiers.append((f"f{fid}", "ckpt_feed"))
        live = jnp.arange(cap) < counts[0]
        return MTable(cols, live, cap, meta["total"], compacted=True)

    def _lower_scan(self, node: TableScanNode) -> MTable:
        import jax.numpy as jnp

        meta = self.scan_meta[id(node)]
        cap = meta["cap"]
        counts = self._traced[meta["counts"]]
        cols = []
        for (vslot, gslot), (typ, d) in zip(meta["slots"], meta["meta"]):
            cols.append(MCol(self._traced[vslot],
                             self._traced[gslot] if gslot is not None
                             else None, typ, d))
        # per-shard scan input rows, summed per fragment at readback
        # (the TaskStats.input_rows feed of the synthetic rollup)
        self._note_stat(("fragment", getattr(self, "_cur_fid", 0),
                         "input_rows"), counts[0])
        live = jnp.arange(cap) < counts[0]
        return MTable(cols, live, cap, meta["total"], compacted=True)

    def _lower_values(self, node: ValuesNode) -> MTable:
        import jax
        import jax.numpy as jnp

        from presto_tpu.parallel.mesh import AXIS

        b = batch_from_pylist(node.types, list(node.rows))
        n = b.num_rows
        cap = next_bucket(max(n, 1), minimum=8)
        b = b.pad_rows(cap)
        cols = []
        for c in b.columns:
            if c.type.is_nested:
                raise MeshUnsupported("nested VALUES")
            valid = None if c.valid is None else jnp.asarray(
                np.asarray(c.valid))
            cols.append(MCol(jnp.asarray(np.asarray(c.values)), valid,
                             c.type, c.dictionary))
        on_first = jax.lax.axis_index(AXIS) == 0
        live = (jnp.arange(cap) < n) & on_first
        return MTable(cols, live, cap, n, compacted=True)

    def _compile(self, exprs: Sequence[RowExpression], table: MTable):
        dicts = {i: c.dictionary for i, c in enumerate(table.cols)
                 if c.dictionary is not None}
        comp = ExprCompiler(dicts)
        return [comp.compile(e) for e in exprs]

    def _lower_filter(self, node: FilterNode) -> MTable:
        import jax.numpy as jnp

        src = self._lower(node.source)
        (ce,) = self._compile([node.predicate], src)
        v, valid = ce.run(src.pairs(), src.cap, jnp)
        mask = v if valid is None else (v & valid)
        return MTable(src.cols, src.live & mask, src.cap, src.est,
                      compacted=False, replicated=src.replicated)

    def _lower_project(self, node: ProjectNode) -> MTable:
        import jax.numpy as jnp

        src = self._lower(node.source)
        compiled = self._compile(list(node.expressions), src)
        cols = []
        for ce, (name, typ) in zip(compiled, node.columns):
            v, valid = ce.run(src.pairs(), src.cap, jnp)
            cols.append(MCol(v, valid, typ, ce.dictionary))
        return MTable(cols, src.live, src.cap, src.est, src.compacted,
                      replicated=src.replicated)

    def _project_table(self, table: MTable,
                       exprs: Sequence[RowExpression]) -> MTable:
        import jax.numpy as jnp

        compiled = self._compile(exprs, table)
        cols = []
        for ce in compiled:
            v, valid = ce.run(table.pairs(), table.cap, jnp)
            cols.append(MCol(v, valid, ce.type, ce.dictionary))
        return MTable(cols, table.live, table.cap, table.est,
                      table.compacted, replicated=table.replicated)

    # ---------------- aggregation ----------------
    def _lower_agg(self, node: AggregationNode) -> MTable:
        import jax.numpy as jnp

        from presto_tpu.ops.groupby import global_aggregate, grouped_aggregate
        from presto_tpu.sql.physical import (
            _finalize, decompose_aggregates, merge_agg_channels,
        )
        fin = _finalize

        if (node.step == "final" and self.nparts == 1
                and isinstance(node.source, RemoteSourceNode)
                and len(node.source.fragment_ids) == 1):
            # Single-device mesh: the partial/final split exists to ride a
            # hash exchange between fragments; with one shard the exchange
            # is an identity and the split just aggregates TWICE over the
            # full capacity.  Fuse back into one single-step aggregation
            # over the partial's source (the whole-query LocalRunner tier
            # always runs here).
            fid = node.source.fragment_ids[0]
            root = self.dplan.fragments[fid].root
            if (isinstance(root, AggregationNode) and root.step == "partial"
                    and fid not in self._cache):
                fused = AggregationNode(root.source, root.group_channels,
                                        node.aggregates, node.columns,
                                        step="single")
                return self._lower_agg(fused)

        src = self._lower(node.source)
        input_types = [t for _, t in node.source.columns]
        ngroups = len(node.group_channels)
        if node.step == "final":
            agg_channels, finalize_specs = merge_agg_channels(
                node.aggregates, ngroups)
        else:
            pre_exprs, agg_channels, finalize_specs = decompose_aggregates(
                node.aggregates, input_types)
            if len(pre_exprs) > len(input_types):
                src = self._project_table(src, pre_exprs)
                input_types = [e.type for e in pre_exprs]
        for ch in agg_channels:
            if ch.prim not in _MESH_PRIMS:
                raise MeshUnsupported(f"agg primitive {ch.prim}")

        aggs = []
        for ch in agg_channels:
            if ch.channel is None:
                # count(*): valid=None counts every live row
                aggs.append(("count", jnp.zeros(src.cap, jnp.int8), None))
                continue
            c = src.cols[ch.channel]
            vals = c.values
            if ch.prim == "sum" and vals.dtype != np.dtype(
                    ch.out_type.np_dtype):
                vals = vals.astype(ch.out_type.np_dtype)
            aggs.append((ch.prim, vals, c.valid))

        if ngroups:
            key_cols = [src.cols[c] for c in node.group_channels]
            direct = self._try_direct_agg(src, key_cols, aggs)
            if direct is not None:
                self.kernel_tiers.append(('groupby', 'direct'))
                out_cols, results, live, cap, est = direct
            else:
                self.kernel_tiers.append(('groupby', 'sort'))
                key_triples = [(c.values, c.valid, c.type) for c in key_cols]
                group_cap = src.cap
                gi, ng, results = grouped_aggregate(
                    key_triples, aggs, src.cap, group_cap,
                    live_mask=src.live)
                self._overflow.append(('groupby', ng > group_cap))
                out_cols = []
                for c in key_cols:
                    out_cols.append(MCol(
                        c.values[gi],
                        None if c.valid is None else c.valid[gi],
                        c.type, c.dictionary))
                live = jnp.arange(group_cap) < jnp.minimum(ng, group_cap)
                cap = group_cap
                est = min(src.est, self.nparts * group_cap)
        else:
            results = global_aggregate(aggs, src.cap, live_mask=src.live)
            out_cols = []
            live = jnp.ones(1, bool)
            cap = 1
            est = self.nparts
        for (vals, cnt), ch in zip(results, agg_channels):
            v = vals if vals.ndim else vals.reshape(1)
            c = cnt if cnt.ndim else cnt.reshape(1)
            valid = None if ch.prim == "count" else (c > 0)
            if v.dtype != np.dtype(ch.out_type.np_dtype):
                v = v.astype(ch.out_type.np_dtype)
            out_cols.append(MCol(v, valid, ch.out_type, None))
        # the direct dense-domain path leaves holes (absent key combos):
        # live rows are NOT a prefix there
        compacted = not (ngroups and direct is not None)
        table = MTable(out_cols, live, cap, est, compacted=compacted,
                       replicated=src.replicated)

        if node.step == "partial":
            return table
        # finalize projection: [keys..., finalized aggregates...]
        key_types = [input_types[c] for c in node.group_channels]
        exprs: List[RowExpression] = [InputRef(i, t)
                                      for i, t in enumerate(key_types)]
        for agg, comps in finalize_specs:
            base = [InputRef(ngroups + ci, agg_channels[ci].out_type)
                    for ci in comps]
            exprs.append(fin(agg, base))
        out = self._project_table(table, exprs)
        out.cols = [MCol(c.values, c.valid, typ, c.dictionary)
                    for c, (_, typ) in zip(out.cols, node.columns)]
        return out

    def _try_direct_agg(self, src: MTable, key_cols, aggs):
        """Dense-domain GROUP BY: when every key is a dictionary code /
        boolean with a trace-time-known domain whose product is small,
        aggregate arithmetically over the dense domain
        (ops.groupby.direct_grouped_aggregate — the BigintGroupByHash
        special-case analogue, ~100x the sort path and the output
        capacity collapses from src.cap to the domain size)."""
        import jax.numpy as jnp

        from presto_tpu.ops.groupby import (
            decode_direct_keys, direct_grouped_aggregate,
        )

        doms: List[int] = []
        for c in key_cols:
            if c.dictionary is not None:
                doms.append(max(1, len(c.dictionary)))
            elif c.type.name == "boolean":
                doms.append(2)
            else:
                return None
        total = 1
        for c, d in zip(key_cols, doms):
            total *= d + (1 if c.valid is not None else 0)
        if total > self.config.direct_groupby_max_domain:
            return None
        key_codes = [(c.values, c.valid) for c in key_cols]
        present, results = direct_grouped_aggregate(
            key_codes, doms, aggs, src.cap, live_mask=src.live)
        D = present.shape[0]
        decoded = decode_direct_keys(
            jnp.arange(D), [c.valid is not None for c in key_cols], doms)
        out_cols: List[MCol] = []
        for c, (codes, valid) in zip(key_cols, decoded):
            out_cols.append(MCol(codes.astype(c.values.dtype),
                                 valid, c.type, c.dictionary))
        # est feeds downstream exchange capacity: a single/gather consumer
        # receives up to nparts * D rows
        return out_cols, results, present, D, min(src.est, self.nparts * D)

    # ---------------- joins ----------------
    def _key_triples(self, table: MTable, channels, other: MTable,
                     other_channels):
        """Join-key triples with dead rows folded into validity and
        dictionary codes unified across sides."""
        import jax.numpy as jnp

        triples_a, triples_b = [], []
        for ca_ch, cb_ch in zip(channels, other_channels):
            ca, cb = table.cols[ca_ch], other.cols[cb_ch]
            va, vb = ca.values, cb.values
            if ca.dictionary is not None or cb.dictionary is not None:
                if ca.dictionary is None or cb.dictionary is None:
                    raise MeshUnsupported("join key mixes string encodings")
                if ca.dictionary is not cb.dictionary:
                    union = Dictionary()
                    ra = ca.dictionary.remap_into(union)
                    rb = cb.dictionary.remap_into(union)
                    va = jnp.asarray(ra)[jnp.clip(va, 0, len(ra) - 1)]
                    vb = jnp.asarray(rb)[jnp.clip(vb, 0, len(rb) - 1)]
            ga = table.live if ca.valid is None else (ca.valid & table.live)
            gb = other.live if cb.valid is None else (cb.valid & other.live)
            triples_a.append((va, ga, ca.type))
            triples_b.append((vb, gb, cb.type))
        return triples_a, triples_b

    def _probe_ranges(self, btrip, ptrip, bcap: int, pcap: int,
                      single: bool, use_pages: bool, label: str):
        """(lo, counts, perm) match ranges per probe row — the shared
        ``(lo, counts)`` contract of ops/join.py, produced by one of the
        three lookup tiers:

        - ``pages_hash`` (P8, ``partitioned_join_build``): the PR 10
          open-addressing table over the shard's key partition — the
          ``PartitionedLookupSource`` role, no total order and no
          key-span limit; a too-full table raises the overflow flag and
          the host re-runs at the next capacity bucket;
        - ``single``: dense ids for one packed integer word;
        - ``sorted``: canonical union-sort ids + binary search.
        """
        from presto_tpu.ops import join as J

        if use_pages:
            from presto_tpu.ops import hashtable as H

            table_cap = next_bucket(2 * self.cap_scale * bcap, minimum=16)
            (words, prefix, used, starts, cnt_t, perm, _has_null,
             ok) = H.pages_hash_build(list(btrip), bcap, table_cap)
            self._overflow.append((f'{label} build table', ~ok))
            lo, counts, _plive = H.pages_hash_probe(
                (words, prefix, used, starts, cnt_t), list(ptrip), pcap)
            self.kernel_tiers.append((label, 'pages_hash'))
            return lo, counts, perm
        if single:
            # a >=2^62 key spread would overflow the dense-id
            # arithmetic; flagging it as overflow makes the runner fail
            # over to the operator tier's canonical path
            self._overflow.append((
                f'{label} key span',
                J.single_word_span_too_big(btrip[0], bcap)))
            bids, pids = J.single_word_ids(btrip[0], ptrip[0], bcap, pcap)
            tier = 'single'
        else:
            bids, pids = J.canonical_ids(btrip, ptrip, bcap, pcap)
            tier = 'sorted'
        sorted_b, perm_b = J.build_index(bids)
        lo, counts = J.probe_counts(sorted_b, perm_b, pids)
        self.kernel_tiers.append((label, tier))
        return lo, counts, perm_b

    def _grouped_expand(self, node: JoinNode, left: MTable, right: MTable,
                        btrip, ptrip, single: bool, use_pages: bool,
                        out_cap: int, B: int):
        """Bucket-sequential grouped execution (P9, §5.7): hash-bucket
        both sides on the join key and run the buckets SEQUENTIALLY
        through the shard-local join, so the per-shard peak intermediate
        (ids / build table / expansion buffers) is ~1/B of the
        single-pass join and SF10-100 builds fit HBM.  Every row belongs
        to exactly one bucket (equal keys co-bucket), so inner and left
        joins emit exactly their single-pass rows; the capacity-bucket
        overflow/rerun policy applies PER BUCKET — a skewed bucket
        raises its flag and the host re-runs at the next cap_scale."""
        import jax.numpy as jnp

        from presto_tpu.ops import join as J
        from presto_tpu.ops.hashing import row_hash
        from presto_tpu.ops.radix import stable_partition_perm

        def bucket_of(triples):
            # a DIFFERENT mix of the key hash than the exchange
            # partition: after a hash exchange every row on this shard
            # has hash % nparts == shard_index, so h % B (both powers
            # of two) would leave most buckets empty
            h = row_hash(list(triples))
            h = ((h ^ jnp.uint64(0x94D049BB133111EB))
                 * jnp.uint64(0x2545F4914F6CDD1D))
            h = h ^ (h >> jnp.uint64(29))
            return (h % jnp.uint64(B)).astype(jnp.int32)

        bb = bucket_of(btrip)
        pb = bucket_of(ptrip)
        # per-bucket working capacities: ~2x the even share (skew head
        # room), clamped to the side capacity — a bucket can never hold
        # more rows than its side, and the clamp keeps gathered shapes
        # consistent when B approaches the side capacity
        bcap = min(next_bucket(
            max(8, (2 * self.cap_scale * right.cap) // B)), right.cap)
        pcap = min(next_bucket(
            max(8, (2 * self.cap_scale * left.cap) // B)), left.cap)
        ecap = min(next_bucket(
            max(8, (2 * self.cap_scale * max(left.cap, right.cap)) // B)),
            out_cap)
        probe_idx = jnp.zeros(out_cap, jnp.int64)
        build_idx = jnp.zeros(out_cap, jnp.int64)
        unmatched = jnp.zeros(out_cap, bool)
        offset = jnp.zeros((), jnp.int64)
        side_overflow = jnp.zeros((), bool)
        expand_overflow = jnp.zeros((), bool)
        for b in range(B):
            mb = right.live & (bb == b)
            mp = left.live & (pb == b)
            ob = stable_partition_perm(~mb)[:bcap].astype(jnp.int32)
            op = stable_partition_perm(~mp)[:pcap].astype(jnp.int32)
            nb = mb.sum()
            np_ = mp.sum()
            side_overflow = side_overflow | (nb > bcap) | (np_ > pcap)
            in_b = jnp.arange(bcap) < nb
            in_p = jnp.arange(pcap) < np_
            btr = [(v[ob], g[ob] & in_b, t) for v, g, t in btrip]
            ptr = [(v[op], g[op] & in_p, t) for v, g, t in ptrip]
            lo, counts, perm = self._probe_ranges(
                btr, ptr, bcap, pcap, single, use_pages,
                label=f'grouped join b{b}')
            if node.kind == "left":
                pi, bi, rv, um, total = J.expand_matches_outer(
                    lo, counts, in_p, perm, ecap)
            else:
                pi, bi, rv, um, total = J.expand_matches(
                    lo, counts, perm, ecap)
            expand_overflow = expand_overflow | (total > ecap)
            # translate bucket-local rows back to shard rows and append
            # this bucket's compacted prefix at the running offset
            dst = jnp.where(rv, offset + jnp.arange(ecap), out_cap)
            probe_idx = probe_idx.at[dst].set(
                op[jnp.clip(pi, 0, pcap - 1)].astype(jnp.int64),
                mode="drop")
            build_idx = build_idx.at[dst].set(
                ob[jnp.clip(bi, 0, bcap - 1)].astype(jnp.int64),
                mode="drop")
            unmatched = unmatched.at[dst].set(um, mode="drop")
            offset = offset + jnp.minimum(total, ecap)
        self._overflow.append(('grouped join side bucket', side_overflow))
        self._overflow.append(('grouped join expand', expand_overflow))
        self._overflow.append(('grouped join total', offset > out_cap))
        row_valid = jnp.arange(out_cap) < offset
        return probe_idx, build_idx, row_valid, unmatched

    def _lower_join(self, node: JoinNode) -> MTable:
        import jax.numpy as jnp

        from presto_tpu.ops import join as J

        left = self._lower(node.left)
        right = self._lower(node.right)
        if node.kind == "cross" or not node.left_keys:
            return self._cross_join(node, left, right)

        btrip, ptrip = self._key_triples(right, node.right_keys,
                                         left, node.left_keys)
        # sides: build = right, probe = left (matches operator tier).
        # Single integer-word keys (ints, dates, decimals, dictionary
        # codes) skip the canonicalization sort entirely: the values ARE
        # the ids (the operator tier's 'single' LookupSource mode).
        single = (len(btrip) == 1 and J.single_word_joinable(
            btrip[0][2],
            right.cols[node.right_keys[0]].dictionary is not None))
        # Partitioned lookup source (P8): the PR 10 open-addressing
        # PagesHash table built per shard over the shard's slice of the
        # build — together the shard tables ARE the global build table
        # sharded across device HBM (probe rows were routed to the
        # owning shard by the hash-exchange all_to_all).  Canonical
        # multi-word keys always take it (equality needs no total
        # order, so the union-sort disappears); packable single-word
        # keys keep the dense-id tier unless the build is large (the
        # hash table has no key-span limit, so big spreads stop failing
        # over to the operator tier).
        use_pages = self.config.partitioned_join_build and (
            not single
            or right.est > self.config.device_join_probe_max_build_rows)
        # Per-shard match capacity: FK-shaped joins emit ~probe-count rows,
        # so the base bucket is max(cap) and cap_scale doubles on overflow
        # retry.  A fixed expansion multiplier would COMPOUND down a join
        # chain (4^depth) — the retry policy pays the cost only when a
        # query actually expands.
        out_cap = next_bucket(
            self.cap_scale * max(left.cap, right.cap), minimum=8)
        B = max(1, int(self.config.grouped_mesh_execution))
        if B > 1:
            probe_idx, build_idx, row_valid, unmatched = \
                self._grouped_expand(node, left, right, btrip, ptrip,
                                     single, use_pages, out_cap, B)
        else:
            lo, counts, perm_b = self._probe_ranges(
                btrip, ptrip, right.cap, left.cap, single, use_pages,
                label='join')
            if node.kind == "left":
                probe_idx, build_idx, row_valid, unmatched, total = \
                    J.expand_matches_outer(lo, counts, left.live, perm_b,
                                           out_cap)
            else:
                probe_idx, build_idx, row_valid, unmatched, total = \
                    J.expand_matches(lo, counts, perm_b, out_cap)
            self._overflow.append(('join', total > out_cap))
        cols: List[MCol] = []
        for c in left.cols:
            valid = None if c.valid is None else c.valid[probe_idx]
            cols.append(MCol(c.values[probe_idx], valid, c.type,
                             c.dictionary))
        for c in right.cols:
            valid = c.valid[build_idx] if c.valid is not None else None
            if node.kind == "left":
                ok = ~unmatched
                valid = ok if valid is None else (valid & ok)
            cols.append(MCol(c.values[build_idx], valid, c.type,
                             c.dictionary))
        if node.kind == "left" and left.replicated \
                and not right.replicated:
            # unmatched probe rows would emit once PER SHARD
            raise MeshUnsupported("left join: replicated probe over "
                                  "sharded build")
        est = max(1, self.cap_scale * max(left.est, right.est))
        table = MTable(cols, row_valid, out_cap, est, compacted=True,
                       replicated=left.replicated and right.replicated)
        if node.residual is not None:
            (ce,) = self._compile([node.residual], table)
            v, valid = ce.run(table.pairs(), table.cap, jnp)
            mask = v if valid is None else (v & valid)
            table = MTable(table.cols, table.live & mask, table.cap,
                           table.est, compacted=False,
                           replicated=table.replicated)
        return table

    def _cross_join(self, node: JoinNode, left: MTable,
                    right: MTable) -> MTable:
        import jax.numpy as jnp

        right = _compact(right)
        if left.cap * right.cap > (1 << 22):
            raise MeshUnsupported("cross join too large for the mesh tier")
        out_cap = left.cap * right.cap
        j = jnp.arange(out_cap)
        li = (j // right.cap).astype(jnp.int32)
        ri = (j % right.cap).astype(jnp.int32)
        live = left.live[li] & right.live[ri]
        cols: List[MCol] = []
        for c in left.cols:
            cols.append(MCol(c.values[li],
                             None if c.valid is None else c.valid[li],
                             c.type, c.dictionary))
        for c in right.cols:
            cols.append(MCol(c.values[ri],
                             None if c.valid is None else c.valid[ri],
                             c.type, c.dictionary))
        est = max(1, left.est * max(right.est, 1))
        table = MTable(cols, live, out_cap, est, compacted=False,
                       replicated=left.replicated and right.replicated)
        if node.residual is not None:
            (ce,) = self._compile([node.residual], table)
            v, valid = ce.run(table.pairs(), table.cap, jnp)
            mask = v if valid is None else (v & valid)
            table.live = table.live & mask
        return table

    def _lower_semijoin(self, node: SemiJoinNode) -> MTable:
        from presto_tpu.ops import join as J

        src = self._lower(node.source)
        filt = self._lower(node.filtering)
        btrip, strip = self._key_triples(filt, node.filtering_keys,
                                         src, node.source_keys)
        if len(btrip) == 1 and J.single_word_joinable(
                btrip[0][2],
                filt.cols[node.filtering_keys[0]].dictionary is not None):
            self._overflow.append((
                'semijoin key span',
                J.single_word_span_too_big(btrip[0], filt.cap)))
            bids, sids = J.single_word_ids(btrip[0], strip[0],
                                           filt.cap, src.cap)
        else:
            bids, sids = J.canonical_ids(btrip, strip, filt.cap, src.cap)
        sorted_b, perm_b = J.build_index(bids)
        lo, counts = J.probe_counts(sorted_b, perm_b, sids)
        if src.replicated and not filt.replicated:
            # each shard would apply only ITS slice of the filtering set
            raise MeshUnsupported("semi join: replicated source over "
                                  "sharded filtering side")
        if node.residual is not None:
            # correlated EXISTS residual (TPC-H Q21 shape): expand key
            # matches, evaluate the residual over [source cols, filtering
            # cols] per candidate pair, reduce any-pass per source row —
            # the operator tier's canonical semi/anti kernel, in-trace
            import jax.numpy as jnp

            out_cap = next_bucket(
                self.cap_scale * max(src.cap, filt.cap), minimum=8)
            pi, bi, rv, _, total = J.expand_matches(lo, counts, perm_b,
                                                    out_cap)
            self._overflow.append(('semijoin residual expand',
                                   total > out_cap))
            pi = pi.astype(jnp.int32)
            bi = bi.astype(jnp.int32)
            pair_cols = []
            for c in src.cols:
                pair_cols.append(MCol(
                    c.values[pi],
                    None if c.valid is None else c.valid[pi],
                    c.type, c.dictionary))
            for c in filt.cols:
                pair_cols.append(MCol(
                    c.values[bi],
                    None if c.valid is None else c.valid[bi],
                    c.type, c.dictionary))
            pairs = MTable(pair_cols, rv, out_cap, src.est,
                           compacted=True, replicated=src.replicated)
            (ce,) = self._compile([node.residual], pairs)
            v, valid = ce.run(pairs.pairs(), out_cap, jnp)
            ok = rv & v
            if valid is not None:
                ok = ok & valid
            matched = (jnp.zeros(src.cap, bool)
                       .at[pi].max(ok, mode="drop"))
            keep = (~matched) if node.negated else matched
            return MTable(src.cols, src.live & keep, src.cap, src.est,
                          compacted=False, replicated=src.replicated)
        if node.negated and node.null_aware:
            import jax.numpy as jnp

            # NOT IN three-valued logic (see ops.join.anti_keep_mask)
            bhn = jnp.zeros((), bool)
            for ch in node.filtering_keys:
                fc = filt.cols[ch]
                if fc.valid is not None:
                    bhn = bhn | (filt.live & ~fc.valid).any()
            if not filt.replicated:
                # filtering rows are sharded: null presence / emptiness
                # are global facts
                import jax

                from presto_tpu.parallel.mesh import AXIS
                bhn = jax.lax.pmax(bhn.astype(jnp.int32), AXIS) > 0
                n_filt = jax.lax.psum(filt.live.sum(), AXIS)
            else:
                n_filt = filt.live.sum()
            mask = J.anti_keep_from_parts(
                counts, sids >= 0, src.live, True,
                [src.cols[ch].valid for ch in node.source_keys],
                n_filt, build_has_null=bhn)
        else:
            mask = J.semi_mask(counts, src.live, node.negated)
        return MTable(src.cols, src.live & mask, src.cap, src.est,
                      compacted=False, replicated=src.replicated)

    # ---------------- order / limit / misc ----------------
    def _sort(self, table: MTable, sort_keys) -> MTable:
        import jax.numpy as jnp

        from presto_tpu.ops.sort import sort_permutation

        table = _compact(table)
        keys = []
        for ch, asc, nulls_first in sort_keys:
            c = table.cols[ch]
            vals = c.values
            if c.dictionary is not None:
                ranks = c.dictionary.sort_ranks()
                if len(ranks) == 0:
                    ranks = np.zeros(1, np.int32)
                vals = jnp.asarray(ranks)[jnp.clip(vals, 0, len(ranks) - 1)]
                typ = T.INTEGER
            else:
                typ = c.type
            keys.append((vals, c.valid, typ, not asc, bool(nulls_first)))
        perm = sort_permutation(keys, table.num_rows).astype(jnp.int32)
        cols = [MCol(c.values[perm],
                     None if c.valid is None else c.valid[perm],
                     c.type, c.dictionary) for c in table.cols]
        return MTable(cols, table.live, table.cap, table.est,
                      compacted=True, replicated=table.replicated)

    def _lower_single_row(self, node: EnforceSingleRowNode) -> MTable:
        import jax.numpy as jnp

        src = _compact(self._lower(node.source))
        n = src.num_rows
        self._errors.append(n > 1)
        cols = []
        for c in src.cols:
            v = c.values[:1]
            ok = (n >= 1)
            valid = (jnp.ones(1, bool) & ok if c.valid is None
                     else c.valid[:1] & ok)
            cols.append(MCol(v, valid, c.type, c.dictionary))
        return MTable(cols, jnp.ones(1, bool), 1, self.nparts,
                      compacted=True, replicated=src.replicated)


def _compact(table: MTable) -> MTable:
    """Move live rows to the front of every shard (stable)."""
    import jax.numpy as jnp

    if table.compacted:
        return table
    from presto_tpu.ops.radix import stable_partition_perm, use_radix

    if use_radix():
        order = stable_partition_perm(~table.live)
    else:
        order = jnp.argsort((~table.live).astype(jnp.int8)).astype(jnp.int32)
    n = table.live.sum()
    cols = [MCol(c.values[order],
                 None if c.valid is None else c.valid[order],
                 c.type, c.dictionary) for c in table.cols]
    live = jnp.arange(table.cap) < n
    return MTable(cols, live, table.cap, table.est, compacted=True,
                  replicated=table.replicated)


def _limit(table: MTable, count: int, nparts: int) -> MTable:
    """Per-shard LIMIT: each shard keeps its first ``count`` live rows,
    so the table may still hold count*nparts rows globally (the consumer
    re-limits after the gather, the reference's partial-limit shape)."""
    import jax.numpy as jnp

    table = _compact(table)
    live = jnp.arange(table.cap) < jnp.minimum(table.num_rows, count)
    return MTable(table.cols, live, table.cap,
                  min(table.est, count * nparts), compacted=True,
                  replicated=table.replicated)


def _concat(tables: List[MTable]) -> MTable:
    """Shard-local UNION ALL: stack padded columns; dictionaries unify."""
    import jax.numpy as jnp

    ncols = len(tables[0].cols)
    cols: List[MCol] = []
    for i in range(ncols):
        parts = [t.cols[i] for t in tables]
        d = None
        if any(p.dictionary is not None for p in parts):
            if not all(p.dictionary is not None for p in parts):
                raise MeshUnsupported("union mixes string encodings")
            d = Dictionary()
            remaps = [p.dictionary.remap_into(d) for p in parts]
            vals = jnp.concatenate([
                jnp.asarray(r)[jnp.clip(p.values, 0, len(r) - 1)]
                for p, r in zip(parts, remaps)])
        else:
            dtype = parts[0].values.dtype
            vals = jnp.concatenate([p.values.astype(dtype) for p in parts])
        if any(p.valid is not None for p in parts):
            valid = jnp.concatenate([
                p.valid if p.valid is not None
                else jnp.ones(t.cap, bool)
                for p, t in zip(parts, tables)])
        else:
            valid = None
        cols.append(MCol(vals, valid, parts[0].type, d))
    live = jnp.concatenate([t.live for t in tables])
    cap = sum(t.cap for t in tables)
    est = sum(t.est for t in tables)
    return MTable(cols, live, cap, est, compacted=False,
                  replicated=all(t.replicated for t in tables))
