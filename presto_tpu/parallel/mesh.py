"""Device mesh construction and row-sharding helpers.

The reference's analogue of a mesh is the worker set tracked by
DiscoveryNodeManager (presto-main/.../metadata/DiscoveryNodeManager.java:68)
plus the bucket-to-node map of NodePartitioningManager
(sql/planner/NodePartitioningManager.java:53).  Here partitions are mesh
shards: a 1-D ``jax.sharding.Mesh`` over the devices of a slice, with the
row dimension of every exchange-partitioned array sharded over it.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "part"


def mesh_fingerprint() -> str:
    """Identity of THIS process's device mesh.  Nodes sharing a
    fingerprint are co-resident on one ``jax.sharding.Mesh`` (same host,
    same process, same device set), so fragment boundaries between tasks
    placed on them can lower to in-program collectives instead of the
    HTTP exchange (the mesh_device_exchange co-residency test).  Workers
    announce it; the coordinator compares every placement's fingerprint
    against its own before choosing the collective tier."""
    import os
    import socket

    devs = jax.devices()
    return (f"{socket.gethostname()}:{os.getpid()}:"
            f"{devs[0].platform}:{len(devs)}")


def make_mesh(n_devices: Optional[int] = None, axis: str = AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)}; tests force a "
                "virtual CPU mesh via XLA_FLAGS=--xla_force_host_platform_"
                "device_count")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def row_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard dim 0 (rows) over the mesh axis; replicate the rest."""
    spec = P(mesh.axis_names[0], *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch_arrays(mesh: Mesh, arrays: Sequence[jax.Array]) -> List[jax.Array]:
    """Place [P*C, ...] global arrays with rows sharded over the mesh."""
    return [jax.device_put(a, row_sharding(mesh, a.ndim)) for a in arrays]
