"""Collective exchange primitives (called inside ``shard_map``).

These are the ICI-native replacements for the reference's exchange data
plane: PagePartitioner.partitionPage's row-at-a-time bucket copy
(presto-main/.../operator/PartitionedOutputOperator.java:377-414) becomes a
vectorized sort-by-destination plus one ``all_to_all``; BroadcastOutputBuffer
(execution/buffer/BroadcastOutputBuffer.java:51) becomes ``all_gather``.
LZ4 serde and token-ack pulls have no intra-slice role — ICI moves raw
device arrays; the host pull protocol survives only across slices/stages
(presto_tpu.dist).

Shape discipline: a shard holds C live-capacity rows and sends a fixed
``slot_cap``-row slot to each of the P peers.  True per-slot counts ride
along; receivers compact live rows to the front.  ``overflow`` is reported
per shard (any send slot truncated, or receive capacity exceeded) so the
host can re-run the step at the next capacity bucket — the distributed
version of the kernels' recompile-on-bucket-change policy.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp


def row_width_bytes(arrays: Sequence) -> int:
    """Static per-row payload width (bytes) of a row-parallel array set
    — the unit the per-shard telemetry multiplies by received-row counts
    to report bytes moved through a collective boundary (the
    device-plane analogue of the wire tier's serialized-page bytes;
    device arrays move raw, so width is just dtype itemsize x trailing
    extent, no serde framing)."""
    import numpy as np

    total = 0
    for a in arrays:
        tail = 1
        for d in a.shape[1:]:
            tail *= int(d)
        total += np.dtype(a.dtype).itemsize * tail
    return total


def repartition(
    arrays: Sequence[jax.Array],
    live: jax.Array,
    dest: jax.Array,
    slot_cap: int,
    out_cap: int,
    axis_name: str,
) -> Tuple[List[jax.Array], jax.Array, jax.Array]:
    """Hash-partitioned exchange (P1, FIXED_HASH_DISTRIBUTION).

    Per-shard view: ``arrays`` are row-parallel [C, ...]; ``live`` marks
    real rows; ``dest`` gives each row's destination shard in [0, P).
    Every shard sends at most ``slot_cap`` rows to each peer and compacts
    what it receives into [out_cap, ...].

    Returns (arrays_out, num_out, overflow) — all per-shard.
    """
    P = jax.lax.axis_size(axis_name)
    C = dest.shape[0]
    d = jnp.where(live, dest.astype(jnp.int32), jnp.int32(P))
    from presto_tpu.ops.radix import counting_sort_perm, use_radix

    if use_radix():
        # single counting pass over the (static) P+1 bucket domain
        order = counting_sort_perm(d, P + 1)
    else:
        order = jnp.argsort(d)  # stable: preserves row order within a bucket
    ds = d[order]
    buckets = jnp.arange(P, dtype=ds.dtype)
    starts = jnp.searchsorted(ds, buckets, side="left")
    ends = jnp.searchsorted(ds, buckets, side="right")
    counts = ends - starts                              # rows per dest
    within = jnp.arange(C) - starts[jnp.clip(ds, 0, P - 1)]
    ok = (ds < P) & (within < slot_cap)
    slot = jnp.where(ok, jnp.clip(ds, 0, P - 1) * slot_cap + within,
                     P * slot_cap)                      # OOB -> dropped
    send_overflow = (counts > slot_cap).any()

    recv_counts = jax.lax.all_to_all(
        jnp.minimum(counts, slot_cap).reshape(P, 1), axis_name,
        split_axis=0, concat_axis=0, tiled=True).reshape(P)
    total = recv_counts.sum()

    # receive-side compaction addresses
    offs = jnp.concatenate([jnp.zeros(1, recv_counts.dtype),
                            jnp.cumsum(recv_counts)[:-1]])
    within_r = jnp.arange(slot_cap)
    live_r = within_r[None, :] < recv_counts[:, None]   # [P, slot_cap]
    dst = jnp.where(live_r, offs[:, None] + within_r[None, :],
                    out_cap).reshape(-1)                # OOB -> dropped

    outs = []
    for a in arrays:
        tail = a.shape[1:]
        buf = jnp.zeros((P * slot_cap,) + tail, a.dtype)
        buf = buf.at[slot].set(a[order], mode="drop")
        recv = jax.lax.all_to_all(
            buf.reshape((P, slot_cap) + tail), axis_name,
            split_axis=0, concat_axis=0, tiled=True)
        out = jnp.zeros((out_cap,) + tail, a.dtype)
        out = out.at[dst].set(recv.reshape((P * slot_cap,) + tail),
                              mode="drop")
        outs.append(out)
    num_out = jnp.minimum(total, out_cap).astype(jnp.int64)
    overflow = send_overflow | (total > out_cap)
    return outs, num_out, overflow


def route_by_key(
    arrays: Sequence[jax.Array],
    live: jax.Array,
    key_triples: Sequence[Tuple[jax.Array, jax.Array, object]],
    slot_cap: int,
    out_cap: int,
    axis_name: str,
) -> Tuple[List[jax.Array], jax.Array, jax.Array]:
    """Hash-route rows to the shard OWNING their key partition (the P8
    ``PartitionedLookupSource`` probe routing, and the P1 hash exchange):
    destination = ``partition_of(row_hash(keys))`` — the SAME per-entry
    value hash the HTTP data plane and partitioned spill use, so every
    tier (wire pages, spool files, in-program collectives, sharded build
    tables) agrees on which shard owns a key.  One ``all_to_all`` moves
    the rows; equal keys land on equal shards, which is what makes a
    shard-local PagesHash table over the received rows a partition of
    the GLOBAL build table (sharded across device HBM)."""
    from presto_tpu.ops.hashing import partition_of, row_hash

    P = jax.lax.axis_size(axis_name)
    dest = partition_of(row_hash(list(key_triples)), P)
    return repartition(arrays, live, dest, slot_cap, out_cap, axis_name)


def broadcast_rows(
    arrays: Sequence[jax.Array],
    num_rows: jax.Array,
    out_cap: int,
    axis_name: str,
) -> Tuple[List[jax.Array], jax.Array, jax.Array]:
    """Broadcast exchange (P2): every shard receives ALL rows, compacted.

    Per-shard view: arrays [C, ...] with ``num_rows`` live.  Result is the
    identical [out_cap, ...] union on every shard (the all-gathered build
    side of a broadcast join).
    """
    P = jax.lax.axis_size(axis_name)
    counts = jax.lax.all_gather(num_rows.reshape(()), axis_name)  # [P]
    total = counts.sum()
    C = arrays[0].shape[0]
    offs = jnp.concatenate([jnp.zeros(1, counts.dtype),
                            jnp.cumsum(counts)[:-1]])
    within = jnp.arange(C)
    live = within[None, :] < counts[:, None]            # [P, C]
    dst = jnp.where(live, offs[:, None] + within[None, :],
                    out_cap).reshape(-1)
    outs = []
    for a in arrays:
        tail = a.shape[1:]
        g = jax.lax.all_gather(a, axis_name, axis=0)    # [P, C, ...]
        out = jnp.zeros((out_cap,) + tail, a.dtype)
        out = out.at[dst].set(g.reshape((P * C,) + tail), mode="drop")
        outs.append(out)
    num_out = jnp.minimum(total, out_cap).astype(jnp.int64)
    return outs, num_out, total > out_cap


def gather_to_first(
    arrays: Sequence[jax.Array],
    num_rows: jax.Array,
    out_cap: int,
    axis_name: str,
) -> Tuple[List[jax.Array], jax.Array, jax.Array]:
    """SINGLE-distribution gather (P4): same data movement as broadcast —
    on TPU the cheap correct move is all_gather; the host then reads one
    shard's copy."""
    return broadcast_rows(arrays, num_rows, out_cap, axis_name)
