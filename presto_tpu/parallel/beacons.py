"""Host-side collector for in-program progress beacons.

The HTTP data plane gets mid-query progress for free: the coordinator's
sampler polls ``/v1/task/{id}`` while a query RUNs.  The collective tier
has no tasks to poll — the whole fragment DAG is ONE ``shard_map``-ped
XLA program — so progress must come OUT of the program: a
``jax.debug.callback`` at every fragment boundary (gated by
``mesh_progress_beacons``) reports (fragment id, shard, rows crossing
the boundary) to whatever sink is installed here for the duration of
the dispatch.

Design constraints this module encodes:

- the compiled program is CACHED across queries, so the callback closure
  must not bind query identity — ``emit`` routes through a process-wide
  "current sink" slot installed around each dispatch (the coordinator
  serializes collective dispatches on ``mesh_executor_lock``, so one
  sink at a time is the actual concurrency);
- callbacks fire on XLA runtime threads, one per shard, possibly
  concurrently — the sink must be thread-safe and ``emit`` must never
  raise into the runtime (a beacon is observability, not control flow).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Set, Tuple

_lock = threading.Lock()
_current: Optional[Callable[[int, int, int], None]] = None


@contextmanager
def install(sink: Optional[Callable[[int, int, int], None]]):
    """Route beacons to ``sink`` for the duration of the block (None =
    drop them, the standalone MeshQueryRunner default)."""
    global _current
    with _lock:
        prev = _current
        _current = sink
    try:
        yield
    finally:
        with _lock:
            _current = prev


def emit(fragment_id, shard, rows) -> None:
    """``jax.debug.callback`` target: one call per shard per fragment
    boundary, with concrete (device-computed) values."""
    with _lock:
        sink = _current
    if sink is None:
        return
    try:
        sink(int(fragment_id), int(shard), int(rows))
    except Exception:  # noqa: BLE001 - observability never fails the program
        pass


class ProgressCollector:
    """Accumulates beacons into a progress snapshot.

    ``units`` are (fragment, shard) pairs — a boundary beacon marks the
    producing fragment complete on that shard, so distinct units only
    grow and every derived surface (completed count, cumulative rows)
    is monotonic by construction.  ``on_progress`` fires under no lock
    with (completed_units, total_units, cumulative_rows) each time a
    NEW unit lands; ``on_beacon`` (test hook) fires on EVERY beacon.
    """

    def __init__(self, total_units: int,
                 on_progress: Optional[Callable[[int, int, int], None]] = None,
                 on_beacon: Optional[Callable[[int, int, int], None]] = None):
        self.total_units = max(int(total_units), 1)
        self.on_progress = on_progress
        self.on_beacon = on_beacon
        self._seen: Set[Tuple[int, int]] = set()
        self._rows: Dict[Tuple[int, int], int] = {}
        self._mutex = threading.Lock()

    def __call__(self, fragment_id: int, shard: int, rows: int) -> None:
        if self.on_beacon is not None:
            self.on_beacon(fragment_id, shard, rows)
        key = (fragment_id, shard)
        with self._mutex:
            fresh = key not in self._seen
            self._seen.add(key)
            # a re-beaconed boundary (multi-consumer fragment) keeps the
            # larger observation; rows never regress
            self._rows[key] = max(self._rows.get(key, 0), rows)
            completed = len(self._seen)
            total_rows = sum(self._rows.values())
        if fresh and self.on_progress is not None:
            self.on_progress(completed, self.total_units, total_rows)

    def snapshot(self) -> Tuple[int, int, int]:
        with self._mutex:
            return (len(self._seen), self.total_units,
                    sum(self._rows.values()))

    def events(self) -> List[Tuple[int, int, int]]:
        with self._mutex:
            return [(f, s, r) for (f, s), r in sorted(self._rows.items())]
