"""Composed multi-chip query steps: one jitted SPMD program per stage pair.

The reference executes a distributed aggregation as PARTIAL agg ->
PartitionedOutput -> exchange -> FINAL agg across worker processes
(HashAggregationOperator.Step:61; AddExchanges.java:114 chooses the
partitioning), and a distributed join as two co-hash-partitioned exchanges
feeding HashBuilder/LookupJoin per node (P1/P8 in SURVEY §2.13).  Here each
such stage pair is ONE ``shard_map``-ped, jitted XLA program over the mesh:
the exchange is an ``all_to_all`` in the middle of the program, so XLA can
overlap it with the surrounding compute — there is no serialized
"serialize page / HTTP / deserialize" hop to hide.

Inputs are global row-sharded arrays ([P*C] with dim 0 over the mesh axis)
plus a per-shard live-row count vector [P]; every column travels as
(values, valid) with an all-True valid standing in for "no nulls" so the
pytree structure is static.  Outputs are per-shard padded blocks [P*cap]
with per-shard counts and overflow flags; the host re-runs at a bigger
capacity bucket on overflow (the distributed rehash policy).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from presto_tpu import types as T
from presto_tpu.ops import join as J
from presto_tpu.ops.groupby import grouped_aggregate
from presto_tpu.ops.hashing import partition_of, row_hash
from presto_tpu.parallel.exchange import broadcast_rows, repartition
from presto_tpu.parallel.mesh import AXIS


def _key_triples(vals, valids, types):
    return [(v, g, t) for v, g, t in zip(vals, valids, types)]


# Final-step merge of a partial aggregate, keyed by the partial's prim:
# count partials are summed; sum partials summed; min/max re-min/maxed.
_FINAL_PRIM = {"count": "sum", "sum": "sum", "min": "min", "max": "max"}


def make_partitioned_aggregate_step(
    key_types: Sequence[T.Type],
    agg_prims: Sequence[str],
    group_cap: int,
    slot_cap: int,
    out_cap: int,
    axis_name: str = AXIS,
):
    """Build the SPMD program for a full distributed GROUP BY:

        local PARTIAL agg -> all_to_all by key hash -> local FINAL agg

    Returned callable (to be jitted under the mesh) takes
    ``(key_vals [K][P*C], key_valids [K][P*C], agg_vals [A][P*C],
    agg_valids [A][P*C], num_rows [P])`` and returns
    ``(out_key_vals [K][P*out_cap], out_key_valids, out_agg_vals
    [A][P*out_cap], out_agg_cnts, num_groups [P], overflow [P])``.
    """
    key_types = list(key_types)
    agg_prims = list(agg_prims)

    def shard_fn(key_vals, key_valids, agg_vals, agg_valids, num_rows):
        n = num_rows[0]
        # ---- PARTIAL: local grouped aggregation --------------------------
        kcols = _key_triples(key_vals, key_valids, key_types)
        agg_ins = list(zip(agg_prims, agg_vals, agg_valids))
        gi, ng, partial = grouped_aggregate(kcols, agg_ins, n, group_cap)
        ng_cap = jnp.minimum(ng, group_cap)
        live = jnp.arange(group_cap) < ng_cap
        pk_vals = [v[gi] for v in key_vals]
        pk_valids = [g[gi] for g in key_valids]
        p_vals = [vals for vals, _ in partial]
        p_cnts = [cnt for _, cnt in partial]
        overflow = ng > group_cap

        # ---- EXCHANGE: co-locate equal keys by hash ----------------------
        h = row_hash(_key_triples(pk_vals, pk_valids, key_types))
        dest = partition_of(h, jax.lax.axis_size(axis_name))
        payload = pk_vals + pk_valids + p_vals + p_cnts
        recv, n_recv, ex_of = repartition(payload, live, dest, slot_cap,
                                          group_cap, axis_name)
        k = len(key_vals)
        a = len(agg_prims)
        rk_vals = recv[:k]
        rk_valids = recv[k:2 * k]
        r_vals = recv[2 * k:2 * k + a]
        r_cnts = recv[2 * k + a:]

        # ---- FINAL: merge partials per key -------------------------------
        fcols = _key_triples(rk_vals, rk_valids, key_types)
        f_ins = []
        for prim, v, c in zip(agg_prims, r_vals, r_cnts):
            if prim == "count":
                f_ins.append(("sum", v, None))
            else:
                f_ins.append((_FINAL_PRIM[prim], v, c > 0))
            f_ins.append(("sum", c.astype(jnp.int64), None))  # merge counts
        fgi, fng, final = grouped_aggregate(fcols, f_ins, n_recv, out_cap)
        out_k_vals = [v[fgi] for v in rk_vals]
        out_k_valids = [g[fgi] for g in rk_valids]
        out_vals, out_cnts = [], []
        for i, prim in enumerate(agg_prims):
            vals, _ = final[2 * i]
            cnts, _ = final[2 * i + 1]
            out_vals.append(vals)
            out_cnts.append(cnts)
        overflow = overflow | ex_of | (fng > out_cap)
        return (out_k_vals, out_k_valids, out_vals, out_cnts,
                fng.reshape(1), overflow.reshape(1))

    k = len(key_types)
    a = len(agg_prims)
    row = P(axis_name)
    in_specs = ([row] * k, [row] * k, [row] * a, [row] * a, row)
    out_specs = ([row] * k, [row] * k, [row] * a, [row] * a, row, row)
    return shard_fn, in_specs, out_specs


def make_partitioned_join_step(
    key_types: Sequence[T.Type],
    n_build_payload: int,
    n_probe_payload: int,
    slot_cap: int,
    local_cap: int,
    out_cap: int,
    axis_name: str = AXIS,
    broadcast_build: bool = False,
):
    """Build the SPMD program for a distributed inner hash join:

        all_to_all both sides by key hash (P1/P8)  -- or --
        all_gather the build side (P2, broadcast join)
        then local sorted-build join per shard.

    Returned callable takes
    ``(b_keys [K][P*C], b_key_valids, b_payload [Nb][P*C],
    p_keys [K][P*C], p_key_valids, p_payload [Np][P*C],
    n_build [P], n_probe [P])`` and returns
    ``(b_payload_out [Nb][P*out_cap], p_payload_out [Np][P*out_cap],
    total [P], overflow [P])`` — the joined rows, per shard.
    """
    key_types = list(key_types)
    nkeys = len(key_types)

    def shard_fn(b_keys, b_key_valids, b_payload,
                 p_keys, p_key_valids, p_payload, n_build, n_probe):
        nb, npr = n_build[0], n_probe[0]
        cap = b_keys[0].shape[0]
        pcap = p_keys[0].shape[0]
        of = jnp.zeros((), bool)

        if broadcast_build:
            bufs, nb, bof = broadcast_rows(
                list(b_keys) + list(b_key_valids) + list(b_payload),
                nb, local_cap, axis_name)
            of = of | bof
            b_keys = bufs[:nkeys]
            b_key_valids = bufs[nkeys:2 * nkeys]
            b_payload = bufs[2 * nkeys:]
        else:
            nparts = jax.lax.axis_size(axis_name)
            hb = row_hash(_key_triples(b_keys, b_key_valids, key_types))
            live_b = jnp.arange(cap) < nb
            bufs, nb, bof = repartition(
                list(b_keys) + list(b_key_valids) + list(b_payload),
                live_b, partition_of(hb, nparts), slot_cap, local_cap,
                axis_name)
            b_keys = bufs[:nkeys]
            b_key_valids = bufs[nkeys:2 * nkeys]
            b_payload = bufs[2 * nkeys:]
            hp = row_hash(_key_triples(p_keys, p_key_valids, key_types))
            live_p = jnp.arange(pcap) < npr
            pufs, npr, pof = repartition(
                list(p_keys) + list(p_key_valids) + list(p_payload),
                live_p, partition_of(hp, nparts), slot_cap, local_cap,
                axis_name)
            p_keys = pufs[:nkeys]
            p_key_valids = pufs[nkeys:2 * nkeys]
            p_payload = pufs[2 * nkeys:]
            of = of | bof | pof

        # ---- local sorted-build join ------------------------------------
        bcols = _key_triples(b_keys, b_key_valids, key_types)
        pcols = _key_triples(p_keys, p_key_valids, key_types)
        bids, pids = J.canonical_ids(bcols, pcols, nb, npr)
        sorted_b, perm_b = J.build_index(bids)
        lo, counts = J.probe_counts(sorted_b, perm_b, pids)
        probe_idx, build_idx, row_valid, _, total = J.expand_matches(
            lo, counts, perm_b, out_cap)
        b_out = [jnp.where(row_valid, a[build_idx], jnp.zeros((), a.dtype))
                 for a in b_payload]
        p_out = [jnp.where(row_valid, a[probe_idx], jnp.zeros((), a.dtype))
                 for a in p_payload]
        of = of | (total > out_cap)
        return (b_out, p_out,
                jnp.minimum(total, out_cap).astype(jnp.int64).reshape(1),
                of.reshape(1))

    row = P(axis_name)
    in_specs = ([row] * nkeys, [row] * nkeys, [row] * n_build_payload,
                [row] * nkeys, [row] * nkeys, [row] * n_probe_payload,
                row, row)
    out_specs = ([row] * n_build_payload, [row] * n_probe_payload, row, row)
    return shard_fn, in_specs, out_specs


def make_partitioned_topn_step(
    sort_types: Sequence[T.Type],
    descending: Sequence[bool],
    n_payload: int,
    limit: int,
    axis_name: str = AXIS,
):
    """Build the SPMD program for a distributed TopN (the mesh analogue
    of the sorted-merge exchange / MergeOperator.java:45 pattern):

        local sort + truncate to ``limit`` candidates per shard
        -> all_gather the candidate blocks over ICI
        -> final sort + truncate, replicated on every shard

    Returned callable takes ``(sort_vals [K][P*C], sort_valids
    [K][P*C], payload [Npay][P*C], num_rows [P])`` and returns
    ``(top_sort_vals [K][limit], top_sort_valids, top_payload
    [Npay][limit], count [])`` — identical (replicated) on every shard,
    so the out specs carry no mesh axis."""
    sort_types = list(sort_types)
    descending = list(descending)
    nkeys = len(sort_types)

    def shard_fn(s_vals, s_valids, payload, num_rows):
        from presto_tpu.ops.sort import sort_permutation

        n = num_rows[0]
        cap = s_vals[0].shape[0]
        keys = [(v, g, t, d, False)
                for v, g, t, d in zip(s_vals, s_valids, sort_types,
                                      descending)]
        perm = sort_permutation(keys, n)
        # per-shard candidate block: min(limit, cap) rows (a shard can
        # contribute at most cap rows; a limit above that is fine — the
        # union below still holds every possible top-limit row because
        # each shard keeps ITS best min(limit, cap))
        block = min(limit, cap)
        top = perm[:block].astype(jnp.int32)
        cand = jnp.minimum(n, block)
        cols = ([v[top] for v in s_vals] + [g[top] for g in s_valids]
                + [p[top] for p in payload])
        # broadcast exchange compacts the ragged candidate blocks into
        # the identical union on every shard (P2 primitive)
        nparts = jax.lax.axis_size(axis_name)
        gathered, total, _of = broadcast_rows(cols, cand,
                                              nparts * block, axis_name)
        g_svals = gathered[:nkeys]
        g_valids = gathered[nkeys:2 * nkeys]
        g_pay = gathered[2 * nkeys:]
        fkeys = [(v, g, t, d, False)
                 for v, g, t, d in zip(g_svals, g_valids, sort_types,
                                       descending)]
        fperm = sort_permutation(fkeys, total)[:limit].astype(jnp.int32)
        out_svals = [v[fperm] for v in g_svals]
        out_valids = [g[fperm] for g in g_valids]
        out_pay = [p[fperm] for p in g_pay]
        return (out_svals, out_valids, out_pay,
                jnp.minimum(total, limit))

    row = P(axis_name)
    rep = P()
    in_specs = ([row] * nkeys, [row] * nkeys, [row] * n_payload, row)
    out_specs = ([rep] * nkeys, [rep] * nkeys, [rep] * n_payload, rep)
    return shard_fn, in_specs, out_specs


def jit_step(mesh, shard_fn, in_specs, out_specs):
    """shard_map + jit a step built by one of the factories above."""
    mapped = jax.shard_map(shard_fn, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
    return jax.jit(mapped)
