"""Multi-chip parallelism: mesh, collective exchange, partitioned operators.

The reference moves data between nodes by hash-partitioning pages into
HTTP-served output buffers (presto-main/.../operator/PartitionedOutputOperator.java:48,
execution/buffer/PartitionedOutputBuffer.java:42) that consumers long-poll
(operator/HttpPageBufferClient.java:297).  Within a TPU slice that entire
data plane becomes XLA collectives over ICI under ``shard_map``:

- P1 FIXED_HASH     -> ``all_to_all``   (exchange.repartition)
- P2 FIXED_BROADCAST-> ``all_gather``   (exchange.broadcast_rows)
- P4 SINGLE         -> gather-to-host   (steps return per-shard results)

(SURVEY §2.13 parallelism inventory.)  Static shapes throughout: every
shard sends fixed-capacity slots and reports true counts; overflow is a
flag the host reacts to by re-running at the next capacity bucket — the
same policy the single-chip kernels use for hash-table growth.
"""

from presto_tpu.parallel.mesh import make_mesh, shard_batch_arrays  # noqa: F401
