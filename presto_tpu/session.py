"""Sessions, session properties, access control, transactions.

The reference splits these across three subsystems that all hang off the
per-query ``Session``:

- **Session properties** (presto-main/.../SystemSessionProperties.java:51,
  147 properties): per-query overrides of engine behavior, set via
  ``SET SESSION k = v``, typed and validated against a registry.
- **Access control** (presto-main/.../security/, presto-spi security SPI;
  file-based impl in presto-plugin-toolkit): table-level permission
  checks made at analysis time with the session identity.
- **Transactions** (presto-main/.../transaction/TransactionManager
  .java:28): one transaction per query (auto-commit), carrying connector
  transaction handles.

Here ``Session`` carries identity + catalog + property overrides and can
materialize an effective ``EngineConfig``; ``AccessControl`` has allow-all
and rule-based implementations; ``TransactionManager`` issues per-query
transaction contexts with commit/abort callbacks into connectors.
"""

from __future__ import annotations

import dataclasses
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from presto_tpu.config import DEFAULT, EngineConfig

# ---------------------------------------------------------------------------
# session properties
# ---------------------------------------------------------------------------

# property name -> (config field, parser); the SystemSessionProperties
# registry: every entry is typed and validated on SET
SESSION_PROPERTIES: Dict[str, Tuple[str, Callable[[str], Any]]] = {
    "spill_enabled": ("spill_enabled",
                      lambda v: v.lower() in ("true", "1", "on")),
    "spill_threshold_bytes": ("spill_threshold_bytes", int),
    "spill_partitions": ("spill_partitions", int),
    "scan_batch_rows": ("scan_batch_rows", int),
    "min_batch_capacity": ("min_batch_capacity", int),
    "task_concurrency": ("task_concurrency", int),
    "join_expansion_factor": ("join_expansion_factor", int),
    "direct_groupby_max_domain": ("direct_groupby_max_domain", int),
    "dynamic_filtering_enabled": ("dynamic_filtering_enabled",
                                  lambda v: v.lower() in ("true", "1",
                                                          "on")),
}


class SessionError(ValueError):
    pass


@dataclasses.dataclass
class Session:
    """Per-connection context (Session.java role)."""

    user: str = "user"
    catalog: str = "tpch"
    schema: Optional[str] = None
    properties: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # PREPARE name FROM stmt storage (Session.preparedStatements role);
    # values are parsed statement trees
    prepared: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # explicit transaction opened by START TRANSACTION (None = autocommit)
    txn: Optional[Any] = None

    def set_property(self, name: str, value: str) -> None:
        name = name.lower()
        if name not in SESSION_PROPERTIES:
            raise SessionError(f"unknown session property {name!r}")
        _, parse = SESSION_PROPERTIES[name]
        try:
            self.properties[name] = parse(value)
        except (ValueError, TypeError) as e:
            raise SessionError(
                f"bad value for session property {name!r}: {value!r}"
            ) from e

    def reset_property(self, name: str) -> None:
        self.properties.pop(name.lower(), None)

    def effective_config(self, base: EngineConfig = DEFAULT) -> EngineConfig:
        if not self.properties:
            return base
        fields = {SESSION_PROPERTIES[k][0]: v
                  for k, v in self.properties.items()}
        return dataclasses.replace(base, **fields)

    def show_properties(self, base: EngineConfig = DEFAULT
                        ) -> List[Tuple[str, str, str]]:
        """(name, value, default) rows for SHOW SESSION."""
        out = []
        for name, (field, _) in sorted(SESSION_PROPERTIES.items()):
            default = getattr(base, field)
            value = self.properties.get(name, default)
            out.append((name, str(value), str(default)))
        return out


# ---------------------------------------------------------------------------
# access control
# ---------------------------------------------------------------------------

class AccessDeniedError(PermissionError):
    pass


class AccessControl:
    """SystemAccessControl SPI surface used by the engine."""

    def check_can_select(self, user: str, catalog: str, table: str) -> None:
        raise NotImplementedError

    def check_can_delete(self, user: str, catalog: str, table: str) -> None:
        # default: DELETE gated like INSERT (write privilege)
        self.check_can_insert(user, catalog, table)

    def check_can_grant(self, user: str, catalog: str, table: str) -> None:
        # default: granting gated like dropping (ownership-level right)
        self.check_can_drop_table(user, catalog, table)

    def check_can_rename_table(self, user: str, catalog: str,
                               table: str) -> None:
        self.check_can_drop_table(user, catalog, table)

    def notify_table_renamed(self, catalog: str, old: str,
                             new: str) -> None:
        """Hook so implementations can migrate per-table state."""

    def check_can_insert(self, user: str, catalog: str, table: str) -> None:
        raise NotImplementedError

    def check_can_create_table(self, user: str, catalog: str,
                               table: str) -> None:
        raise NotImplementedError

    def check_can_drop_table(self, user: str, catalog: str,
                             table: str) -> None:
        raise NotImplementedError


class AllowAllAccessControl(AccessControl):
    def check_can_select(self, user, catalog, table):
        pass

    def check_can_insert(self, user, catalog, table):
        pass

    def check_can_create_table(self, user, catalog, table):
        pass

    def check_can_drop_table(self, user, catalog, table):
        pass


class RuleBasedAccessControl(AccessControl):
    """The file-based access control model (presto-plugin-toolkit's
    FileBasedSystemAccessControl): ordered rules of
    {user, catalog, table, privileges}; first match wins, no match denies.
    Patterns are '*'-wildcards."""

    def __init__(self, rules: List[Dict[str, Any]]):
        self.rules = rules

    @staticmethod
    def _match(pattern: str, value: str) -> bool:
        import fnmatch

        return fnmatch.fnmatch(value, pattern)

    def _check(self, user: str, catalog: str, table: str,
               privilege: str) -> None:
        for rule in self.rules:
            if not self._match(rule.get("user", "*"), user):
                continue
            if not self._match(rule.get("catalog", "*"), catalog):
                continue
            if not self._match(rule.get("table", "*"), table):
                continue
            if privilege in rule.get("privileges", ()):
                return
            break  # first matching rule decides
        raise AccessDeniedError(
            f"Access denied: {user} cannot {privilege} "
            f"{catalog}.{table}")

    def check_can_select(self, user, catalog, table):
        self._check(user, catalog, table, "select")

    def check_can_insert(self, user, catalog, table):
        self._check(user, catalog, table, "insert")

    def check_can_create_table(self, user, catalog, table):
        self._check(user, catalog, table, "create")

    def check_can_drop_table(self, user, catalog, table):
        self._check(user, catalog, table, "drop")

    def check_can_delete(self, user, catalog, table):
        self._check(user, catalog, table, "delete")

    def check_can_grant(self, user, catalog, table):
        self._check(user, catalog, table, "grant")


class GrantStore:
    """SQL-managed privileges: GRANT/REVOKE state, keyed
    (user, catalog, table) -> set of privileges ('all' covers every
    privilege).  Thread-safe; shared by every session of a runner."""

    def __init__(self):
        self._lock = threading.Lock()
        self._grants: Dict[Tuple[str, str, str], set] = {}

    def grant(self, user: str, catalog: str, table: str,
              privileges) -> None:
        with self._lock:
            self._grants.setdefault((user, catalog, table),
                                    set()).update(privileges)

    def revoke(self, user: str, catalog: str, table: str,
               privileges) -> None:
        with self._lock:
            have = self._grants.get((user, catalog, table))
            if have:
                have.difference_update(privileges)

    def has(self, user: str, catalog: str, table: str,
            privilege: str) -> bool:
        with self._lock:
            have = self._grants.get((user, catalog, table), set())
            return privilege in have or "all" in have

    def rename_table(self, catalog: str, old: str, new: str) -> None:
        """Migrate grants when a table is renamed."""
        with self._lock:
            for key in [k for k in self._grants
                        if k[1] == catalog and k[2] == old]:
                self._grants[(key[0], catalog, new)] = \
                    self._grants.pop(key)


class GrantAwareAccessControl(AccessControl):
    """Access control driven by the GrantStore: the table owner (creator)
    and any ``admin_users`` bypass checks; everyone else needs an explicit
    GRANT.  This is the SQL-standard access-control mode of the reference
    (sql-standard AccessControl in presto-hive, GRANT/REVOKE in
    StatementAnalyzer)."""

    def __init__(self, grants: Optional[GrantStore] = None,
                 admin_users=("admin",)):
        # when None, the runner binds its shared GrantStore at attach time
        self.grants = grants
        self.admins = set(admin_users)
        self._owners: Dict[Tuple[str, str], str] = {}

    def _check(self, user, catalog, table, privilege):
        if user in self.admins:
            return
        if self._owners.get((catalog, table)) == user:
            return
        if self.grants.has(user, catalog, table, privilege):
            return
        raise AccessDeniedError(
            f"Access denied: {user} cannot {privilege} {catalog}.{table}")

    def check_can_select(self, user, catalog, table):
        self._check(user, catalog, table, "select")

    def check_can_insert(self, user, catalog, table):
        self._check(user, catalog, table, "insert")

    def check_can_create_table(self, user, catalog, table):
        # first creator wins: never steal ownership when the table
        # already exists (the create itself will fail later)
        self._owners.setdefault((catalog, table), user)

    def check_can_drop_table(self, user, catalog, table):
        if user in self.admins or self._owners.get(
                (catalog, table)) == user:
            return
        self._check(user, catalog, table, "drop")

    def check_can_delete(self, user, catalog, table):
        self._check(user, catalog, table, "delete")

    def notify_table_renamed(self, catalog, old, new):
        if (catalog, old) in self._owners:
            self._owners[(catalog, new)] = self._owners.pop((catalog, old))
        if self.grants is not None:
            self.grants.rename_table(catalog, old, new)


# ---------------------------------------------------------------------------
# transactions
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TransactionInfo:
    transaction_id: str
    auto_commit: bool = True
    # connector-side commit/abort callbacks registered during execution
    commit_actions: List[Callable[[], None]] = dataclasses.field(
        default_factory=list)
    abort_actions: List[Callable[[], None]] = dataclasses.field(
        default_factory=list)
    state: str = "ACTIVE"          # ACTIVE | COMMITTED | ABORTED


class TransactionManager:
    """Per-query auto-commit transactions (TransactionManager.java:28).
    The engine's writes are single-commit PageSink finishes; the manager
    sequences those commits and exposes abort for failure paths."""

    def __init__(self):
        self._lock = threading.Lock()
        self.transactions: Dict[str, TransactionInfo] = {}

    def begin(self, auto_commit: bool = True) -> TransactionInfo:
        txn = TransactionInfo(uuid.uuid4().hex[:16], auto_commit)
        with self._lock:
            self.transactions[txn.transaction_id] = txn
        return txn

    def commit(self, txn: TransactionInfo) -> None:
        if txn.state != "ACTIVE":
            raise RuntimeError(f"transaction is {txn.state}")
        for action in txn.commit_actions:
            action()
        txn.state = "COMMITTED"
        self._forget(txn)

    def abort(self, txn: TransactionInfo) -> None:
        if txn.state != "ACTIVE":
            return
        for action in txn.abort_actions:
            try:
                action()
            except Exception:  # noqa: BLE001 - abort is best-effort
                pass
        txn.state = "ABORTED"
        self._forget(txn)

    def _forget(self, txn: TransactionInfo) -> None:
        with self._lock:
            self.transactions.pop(txn.transaction_id, None)


# ---------------------------------------------------------------------------
# resource groups
# ---------------------------------------------------------------------------

class QueryQueueFullError(RuntimeError):
    pass


class ResourceGroup:
    """One node of the admission-control tree
    (InternalResourceGroup.java:77): bounded running + queued queries,
    FIFO release.  ``hard_concurrency_limit`` / ``max_queued`` follow the
    reference's property names."""

    def __init__(self, name: str, hard_concurrency_limit: int = 16,
                 max_queued: int = 64,
                 parent: Optional["ResourceGroup"] = None):
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self.parent = parent
        self.running = 0
        self.queued = 0
        self._cond = threading.Condition(
            parent._cond if parent is not None else threading.Lock())

    def _can_run_locked(self) -> bool:
        node: Optional[ResourceGroup] = self
        while node is not None:
            if node.running >= node.hard_concurrency_limit:
                return False
            node = node.parent
        return True

    def acquire(self, timeout_s: Optional[float] = None) -> None:
        """Block until a run slot frees; raise when the queue is full."""
        with self._cond:
            if self._can_run_locked():
                self._grab_locked()
                return
            if self.queued >= self.max_queued:
                raise QueryQueueFullError(
                    f"Too many queued queries for {self.name!r}")
            self.queued += 1
            try:
                ok = self._cond.wait_for(self._can_run_locked,
                                         timeout=timeout_s)
                if not ok:
                    raise QueryQueueFullError(
                        f"queue wait timed out for {self.name!r}")
                self._grab_locked()
            finally:
                self.queued -= 1

    def _grab_locked(self) -> None:
        node: Optional[ResourceGroup] = self
        while node is not None:
            node.running += 1
            node = node.parent

    def release(self) -> None:
        with self._cond:
            node: Optional[ResourceGroup] = self
            while node is not None:
                node.running -= 1
                node = node.parent
            self._cond.notify_all()


class ResourceGroupManager:
    """Selects the group for a session (the rule-based selector role:
    per-user groups under a root)."""

    def __init__(self, hard_concurrency_limit: int = 16,
                 max_queued: int = 64, per_user_limit: int = 8):
        self.root = ResourceGroup("global", hard_concurrency_limit,
                                  max_queued)
        self.per_user_limit = per_user_limit
        self._groups: Dict[str, ResourceGroup] = {}
        self._lock = threading.Lock()

    def group_for(self, session: Session) -> ResourceGroup:
        with self._lock:
            g = self._groups.get(session.user)
            if g is None:
                g = ResourceGroup(f"global.{session.user}",
                                  self.per_user_limit,
                                  self.root.max_queued, parent=self.root)
                self._groups[session.user] = g
            return g


# ---------------------------------------------------------------------------
# session property managers
# ---------------------------------------------------------------------------

class SessionPropertyManager:
    """Rule-based session property defaults
    (presto-session-property-managers role: the db/file-backed
    SessionPropertyConfigurationManager applies matching rules'
    properties to a session before execution; explicit SET SESSION
    values still win).

    Rules are ordered dicts: {"user": pattern, "source": pattern,
    "properties": {name: value}}; '*' wildcards; all matching rules
    apply, later rules overriding earlier ones."""

    def __init__(self, rules: List[Dict[str, Any]]):
        self.rules = list(rules)

    @staticmethod
    def _match(pattern: str, value: str) -> bool:
        import fnmatch

        return fnmatch.fnmatch(value, pattern)

    def defaults_for(self, user: str, source: str = "") -> Dict[str, str]:
        out: Dict[str, str] = {}
        for rule in self.rules:
            if not self._match(rule.get("user", "*"), user):
                continue
            if not self._match(rule.get("source", "*"), source):
                continue
            out.update(rule.get("properties", {}))
        return out

    def apply(self, session: "Session", source: str = "") -> None:
        """Set matched defaults that the session has not set itself."""
        for name, value in self.defaults_for(session.user,
                                             source).items():
            if name.lower() not in session.properties:
                session.set_property(name, str(value))
