"""Sessions, session properties, access control, transactions.

The reference splits these across three subsystems that all hang off the
per-query ``Session``:

- **Session properties** (presto-main/.../SystemSessionProperties.java:51,
  147 properties): per-query overrides of engine behavior, set via
  ``SET SESSION k = v``, typed and validated against a registry.
- **Access control** (presto-main/.../security/, presto-spi security SPI;
  file-based impl in presto-plugin-toolkit): table-level permission
  checks made at analysis time with the session identity.
- **Transactions** (presto-main/.../transaction/TransactionManager
  .java:28): one transaction per query (auto-commit), carrying connector
  transaction handles.

Here ``Session`` carries identity + catalog + property overrides and can
materialize an effective ``EngineConfig``; ``AccessControl`` has allow-all
and rule-based implementations; ``TransactionManager`` issues per-query
transaction contexts with commit/abort callbacks into connectors.
"""

from __future__ import annotations

import dataclasses
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from presto_tpu.config import DEFAULT, EngineConfig

# ---------------------------------------------------------------------------
# session properties
# ---------------------------------------------------------------------------

def _enum_parser(name: str, allowed: Tuple[str, ...]):
    def parse(v: str) -> str:
        lv = v.lower()
        if lv not in allowed:
            raise ValueError(
                f"{name} must be one of {', '.join(allowed)}")
        return lv

    return parse


# property name -> (config field, parser); the SystemSessionProperties
# registry: every entry is typed and validated on SET
SESSION_PROPERTIES: Dict[str, Tuple[str, Callable[[str], Any]]] = {
    "spill_enabled": ("spill_enabled",
                      lambda v: v.lower() in ("true", "1", "on")),
    "spill_threshold_bytes": ("spill_threshold_bytes", int),
    "spill_partitions": ("spill_partitions", int),
    "scan_batch_rows": ("scan_batch_rows", int),
    "min_batch_capacity": ("min_batch_capacity", int),
    "task_concurrency": ("task_concurrency", int),
    "join_expansion_factor": ("join_expansion_factor", int),
    "direct_groupby_max_domain": ("direct_groupby_max_domain", int),
    "dynamic_filtering_enabled": ("dynamic_filtering_enabled",
                                  lambda v: v.lower() in ("true", "1",
                                                          "on")),
    "pipeline_fusion": ("pipeline_fusion",
                        lambda v: v.lower() in ("true", "1", "on")),
    "fusion_partial_agg": ("fusion_partial_agg",
                           lambda v: v.lower() in ("true", "1", "on")),
    "kernel_cache_capacity": ("kernel_cache_capacity", int),
    "whole_query_execution": ("whole_query_execution",
                              lambda v: v.lower() in ("true", "1", "on")),
    "streaming_aggregation_enabled": (
        "streaming_aggregation_enabled",
        lambda v: v.lower() in ("true", "1", "on")),
    "grouped_execution_buckets": ("grouped_execution_buckets", int),
    "join_distribution_type": ("join_distribution_type", _enum_parser(
        "join_distribution_type",
        ("automatic", "broadcast", "partitioned"))),
    "broadcast_join_row_limit": ("broadcast_join_row_limit", int),
    "join_reordering_strategy": ("join_reordering_strategy", _enum_parser(
        "join_reordering_strategy", ("automatic", "none"))),
    "optimizer_use_memo": ("optimizer_use_memo",
                           lambda v: v.lower() in ("true", "1", "on")),
    "memo_max_reorder_relations": ("memo_max_reorder_relations", int),
    "partial_aggregation_enabled": (
        "partial_aggregation_enabled",
        lambda v: v.lower() in ("true", "1", "on")),
    "scaled_writer_rows_per_task": ("scaled_writer_rows_per_task", int),
    "hash_partition_count": ("hash_partition_count", int),
    "query_max_memory_bytes": ("query_max_memory_bytes", int),
    # cluster-wide (summed over every worker) per-query reservation cap,
    # enforced by the coordinator's memory tick
    "query_max_total_memory_bytes": ("query_max_total_memory_bytes",
                                     int),
    "query_max_run_time_s": ("query_max_run_time_s", float),
    "stage_retry_limit": ("stage_retry_limit", int),
    "cancel_fanout_budget_s": ("cancel_fanout_budget_s", float),
    "speculative_execution_enabled": (
        "speculative_execution_enabled",
        lambda v: v.lower() in ("true", "1", "on")),
    "speculation_quantile": ("speculation_quantile", float),
    "speculation_lag_factor": ("speculation_lag_factor", float),
    "speculation_min_runtime_s": ("speculation_min_runtime_s", float),
    "exchange_spooling_enabled": (
        "exchange_spooling_enabled",
        lambda v: v.lower() in ("true", "1", "on")),
    "exchange_max_buffer_bytes": ("exchange_max_buffer_bytes", int),
    "exchange_spool_stall_s": ("exchange_spool_stall_s", float),
    "plan_cache_enabled": ("plan_cache_enabled",
                           lambda v: v.lower() in ("true", "1", "on")),
    "plan_cache_capacity": ("plan_cache_capacity", int),
    "result_cache_enabled": (
        "result_cache_enabled",
        lambda v: v.lower() in ("true", "1", "on")),
    "result_cache_max_entry_bytes": ("result_cache_max_entry_bytes",
                                     int),
    "query_queue_timeout_s": ("query_queue_timeout_s", float),
    "hash_groupby_enabled": (
        "hash_groupby_enabled",
        lambda v: v.lower() in ("true", "1", "on")),
    "hash_groupby_init_slots": ("hash_groupby_init_slots", int),
    "hash_groupby_max_slots": ("hash_groupby_max_slots", int),
    "hash_groupby_min_rows": ("hash_groupby_min_rows", int),
    "device_join_probe": (
        "device_join_probe",
        lambda v: v.lower() in ("true", "1", "on")),
    "device_join_probe_max_build_rows": (
        "device_join_probe_max_build_rows", int),
    "fusion_final_merge": (
        "fusion_final_merge",
        lambda v: v.lower() in ("true", "1", "on")),
    "prereduce_cost_based": (
        "prereduce_cost_based",
        lambda v: v.lower() in ("true", "1", "on")),
    "prereduce_max_group_fraction": (
        "prereduce_max_group_fraction", float),
    "mesh_device_exchange": (
        "mesh_device_exchange",
        lambda v: v.lower() in ("true", "1", "on")),
    "partitioned_join_build": (
        "partitioned_join_build",
        lambda v: v.lower() in ("true", "1", "on")),
    "grouped_mesh_execution": ("grouped_mesh_execution", int),
    "mesh_progress_beacons": (
        "mesh_progress_beacons",
        lambda v: v.lower() in ("true", "1", "on")),
    "mesh_checkpoint_boundaries": (
        "mesh_checkpoint_boundaries",
        lambda v: v.lower() in ("true", "1", "on")),
    "mesh_resume_mode": ("mesh_resume_mode", str),
    "stats_sampling_enabled": (
        "stats_sampling_enabled",
        lambda v: v.lower() in ("true", "1", "on")),
    "stats_sample_interval_s": ("stats_sample_interval_s", float),
    "slow_query_log_threshold_s": ("slow_query_log_threshold_s", float),
}


class SessionError(ValueError):
    pass


@dataclasses.dataclass
class Session:
    """Per-connection context (Session.java role)."""

    user: str = "user"
    catalog: str = "tpch"
    schema: Optional[str] = None
    properties: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # PREPARE name FROM stmt storage (Session.preparedStatements role);
    # values are parsed statement trees
    prepared: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # explicit transaction opened by START TRANSACTION (None = autocommit)
    txn: Optional[Any] = None

    def set_property(self, name: str, value: str) -> None:
        name = name.lower()
        if name not in SESSION_PROPERTIES:
            raise SessionError(f"unknown session property {name!r}")
        _, parse = SESSION_PROPERTIES[name]
        try:
            self.properties[name] = parse(value)
        except (ValueError, TypeError) as e:
            raise SessionError(
                f"bad value for session property {name!r}: {value!r}"
            ) from e

    def reset_property(self, name: str) -> None:
        self.properties.pop(name.lower(), None)

    def effective_config(self, base: EngineConfig = DEFAULT) -> EngineConfig:
        if not self.properties:
            return base
        fields = {SESSION_PROPERTIES[k][0]: v
                  for k, v in self.properties.items()}
        return dataclasses.replace(base, **fields)

    def show_properties(self, base: EngineConfig = DEFAULT
                        ) -> List[Tuple[str, str, str]]:
        """(name, value, default) rows for SHOW SESSION."""
        out = []
        for name, (field, _) in sorted(SESSION_PROPERTIES.items()):
            default = getattr(base, field)
            value = self.properties.get(name, default)
            out.append((name, str(value), str(default)))
        return out


# ---------------------------------------------------------------------------
# access control
# ---------------------------------------------------------------------------

class AccessDeniedError(PermissionError):
    pass


class AccessControl:
    """SystemAccessControl SPI surface used by the engine."""

    def check_can_select(self, user: str, catalog: str, table: str) -> None:
        raise NotImplementedError

    def check_can_delete(self, user: str, catalog: str, table: str) -> None:
        # default: DELETE gated like INSERT (write privilege)
        self.check_can_insert(user, catalog, table)

    def check_can_grant(self, user: str, catalog: str, table: str) -> None:
        # default: granting gated like dropping (ownership-level right)
        self.check_can_drop_table(user, catalog, table)

    def check_can_rename_table(self, user: str, catalog: str,
                               table: str) -> None:
        self.check_can_drop_table(user, catalog, table)

    def notify_table_renamed(self, catalog: str, old: str,
                             new: str) -> None:
        """Hook so implementations can migrate per-table state."""

    def check_can_insert(self, user: str, catalog: str, table: str) -> None:
        raise NotImplementedError

    def check_can_create_table(self, user: str, catalog: str,
                               table: str) -> None:
        raise NotImplementedError

    def check_can_drop_table(self, user: str, catalog: str,
                             table: str) -> None:
        raise NotImplementedError


class AllowAllAccessControl(AccessControl):
    def check_can_select(self, user, catalog, table):
        pass

    def check_can_insert(self, user, catalog, table):
        pass

    def check_can_create_table(self, user, catalog, table):
        pass

    def check_can_drop_table(self, user, catalog, table):
        pass


class RuleBasedAccessControl(AccessControl):
    """The file-based access control model (presto-plugin-toolkit's
    FileBasedSystemAccessControl): ordered rules of
    {user, catalog, table, privileges}; first match wins, no match denies.
    Patterns are '*'-wildcards."""

    def __init__(self, rules: List[Dict[str, Any]]):
        self.rules = rules

    @staticmethod
    def _match(pattern: str, value: str) -> bool:
        import fnmatch

        return fnmatch.fnmatch(value, pattern)

    def _check(self, user: str, catalog: str, table: str,
               privilege: str) -> None:
        for rule in self.rules:
            if not self._match(rule.get("user", "*"), user):
                continue
            if not self._match(rule.get("catalog", "*"), catalog):
                continue
            if not self._match(rule.get("table", "*"), table):
                continue
            if privilege in rule.get("privileges", ()):
                return
            break  # first matching rule decides
        raise AccessDeniedError(
            f"Access denied: {user} cannot {privilege} "
            f"{catalog}.{table}")

    def check_can_select(self, user, catalog, table):
        self._check(user, catalog, table, "select")

    def check_can_insert(self, user, catalog, table):
        self._check(user, catalog, table, "insert")

    def check_can_create_table(self, user, catalog, table):
        self._check(user, catalog, table, "create")

    def check_can_drop_table(self, user, catalog, table):
        self._check(user, catalog, table, "drop")

    def check_can_delete(self, user, catalog, table):
        self._check(user, catalog, table, "delete")

    def check_can_grant(self, user, catalog, table):
        self._check(user, catalog, table, "grant")


class GrantStore:
    """SQL-managed privileges: GRANT/REVOKE state, keyed
    (user, catalog, table) -> set of privileges ('all' covers every
    privilege).  Thread-safe; shared by every session of a runner."""

    def __init__(self):
        self._lock = threading.Lock()
        self._grants: Dict[Tuple[str, str, str], set] = {}

    def grant(self, user: str, catalog: str, table: str,
              privileges) -> None:
        with self._lock:
            self._grants.setdefault((user, catalog, table),
                                    set()).update(privileges)

    def revoke(self, user: str, catalog: str, table: str,
               privileges) -> None:
        with self._lock:
            have = self._grants.get((user, catalog, table))
            if have:
                have.difference_update(privileges)

    def has(self, user: str, catalog: str, table: str,
            privilege: str) -> bool:
        with self._lock:
            have = self._grants.get((user, catalog, table), set())
            return privilege in have or "all" in have

    def rename_table(self, catalog: str, old: str, new: str) -> None:
        """Migrate grants when a table is renamed."""
        with self._lock:
            for key in [k for k in self._grants
                        if k[1] == catalog and k[2] == old]:
                self._grants[(key[0], catalog, new)] = \
                    self._grants.pop(key)


class GrantAwareAccessControl(AccessControl):
    """Access control driven by the GrantStore: the table owner (creator)
    and any ``admin_users`` bypass checks; everyone else needs an explicit
    GRANT.  This is the SQL-standard access-control mode of the reference
    (sql-standard AccessControl in presto-hive, GRANT/REVOKE in
    StatementAnalyzer)."""

    def __init__(self, grants: Optional[GrantStore] = None,
                 admin_users=("admin",)):
        # when None, the runner binds its shared GrantStore at attach time
        self.grants = grants
        self.admins = set(admin_users)
        self._owners: Dict[Tuple[str, str], str] = {}

    def _check(self, user, catalog, table, privilege):
        if user in self.admins:
            return
        if self._owners.get((catalog, table)) == user:
            return
        if self.grants.has(user, catalog, table, privilege):
            return
        raise AccessDeniedError(
            f"Access denied: {user} cannot {privilege} {catalog}.{table}")

    def check_can_select(self, user, catalog, table):
        self._check(user, catalog, table, "select")

    def check_can_insert(self, user, catalog, table):
        self._check(user, catalog, table, "insert")

    def check_can_create_table(self, user, catalog, table):
        # first creator wins: never steal ownership when the table
        # already exists (the create itself will fail later)
        self._owners.setdefault((catalog, table), user)

    def check_can_drop_table(self, user, catalog, table):
        if user in self.admins or self._owners.get(
                (catalog, table)) == user:
            return
        self._check(user, catalog, table, "drop")

    def check_can_delete(self, user, catalog, table):
        self._check(user, catalog, table, "delete")

    def notify_table_renamed(self, catalog, old, new):
        if (catalog, old) in self._owners:
            self._owners[(catalog, new)] = self._owners.pop((catalog, old))
        if self.grants is not None:
            self.grants.rename_table(catalog, old, new)


# ---------------------------------------------------------------------------
# transactions
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TransactionInfo:
    transaction_id: str
    auto_commit: bool = True
    # connector-side commit/abort callbacks registered during execution
    commit_actions: List[Callable[[], None]] = dataclasses.field(
        default_factory=list)
    abort_actions: List[Callable[[], None]] = dataclasses.field(
        default_factory=list)
    state: str = "ACTIVE"          # ACTIVE | COMMITTED | ABORTED


class TransactionManager:
    """Per-query auto-commit transactions (TransactionManager.java:28).
    The engine's writes are single-commit PageSink finishes; the manager
    sequences those commits and exposes abort for failure paths."""

    def __init__(self):
        self._lock = threading.Lock()
        self.transactions: Dict[str, TransactionInfo] = {}

    def begin(self, auto_commit: bool = True) -> TransactionInfo:
        txn = TransactionInfo(uuid.uuid4().hex[:16], auto_commit)
        with self._lock:
            self.transactions[txn.transaction_id] = txn
        return txn

    def commit(self, txn: TransactionInfo) -> None:
        if txn.state != "ACTIVE":
            raise RuntimeError(f"transaction is {txn.state}")
        for action in txn.commit_actions:
            action()
        txn.state = "COMMITTED"
        self._forget(txn)

    def abort(self, txn: TransactionInfo) -> None:
        if txn.state != "ACTIVE":
            return
        for action in txn.abort_actions:
            try:
                action()
            except Exception:  # noqa: BLE001 - abort is best-effort
                pass
        txn.state = "ABORTED"
        self._forget(txn)

    def _forget(self, txn: TransactionInfo) -> None:
        with self._lock:
            self.transactions.pop(txn.transaction_id, None)


# ---------------------------------------------------------------------------
# resource groups
# ---------------------------------------------------------------------------

class QueryQueueFullError(RuntimeError):
    pass


class QueryCancelledError(RuntimeError):
    """A queued admission wait was cancelled (DELETE on a QUEUED query):
    the waiter is dequeued without ever consuming a slot."""


class _Ticket:
    """One queued admission request (ordering handle)."""

    __slots__ = ("seq", "group")

    def __init__(self, seq: int, group: "ResourceGroup"):
        self.seq = seq
        self.group = group


class ResourceGroup:
    """One node of the admission-control tree
    (InternalResourceGroup.java:77,91,95): bounded running + queued
    queries, policy-driven release order, and a soft memory limit that
    stops NEW admissions while the group's tracked usage exceeds it.
    ``hard_concurrency_limit`` / ``max_queued`` / ``soft_memory_limit`` /
    ``scheduling_policy`` / ``scheduling_weight`` follow the reference's
    property names.

    Policies decide which child subtree's waiter runs when a slot frees:
    - 'fair' (default): the child with the fewest running queries, FIFO
      within a child (the reference's fair queue);
    - 'weighted_fair': the child with the lowest running/weight ratio
      (WeightedFairQueue.java role);
    - 'query_priority': strict FIFO over every waiter in the subtree.
    """

    def __init__(self, name: str, hard_concurrency_limit: int = 16,
                 max_queued: int = 64,
                 parent: Optional["ResourceGroup"] = None,
                 scheduling_weight: int = 1,
                 scheduling_policy: str = "fair",
                 soft_memory_limit_bytes: Optional[int] = None,
                 hard_cpu_limit_s: Optional[float] = None,
                 cpu_quota_generation_s_per_s: float = 0.0):
        import time as _time

        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        self.parent = parent
        self.scheduling_weight = max(int(scheduling_weight), 1)
        self.scheduling_policy = scheduling_policy
        self.soft_memory_limit_bytes = soft_memory_limit_bytes
        self.memory_usage = 0
        # CPU accounting (InternalResourceGroup cpuUsageMillis /
        # hardCpuLimit / cpuQuotaGenerationMillisPerSecond role): queries
        # charge their execution seconds at completion; a group over its
        # hard CPU limit admits nothing until the regeneration rate pays
        # the debt back down.  None = no CPU limit.
        self.cpu_usage_s = 0.0
        self.hard_cpu_limit_s = hard_cpu_limit_s
        self.cpu_quota_generation_s_per_s = cpu_quota_generation_s_per_s
        self._cpu_regen_at = _time.monotonic()
        self.running = 0
        self.queued = 0
        self.children: List["ResourceGroup"] = []
        self._queue: List[_Ticket] = []   # this group's own waiters, FIFO
        # ONE condition per tree: a release in any group must be able to
        # wake a waiter in a sibling (the policy walk decides which)
        self._cond = (parent._cond if parent is not None
                      else threading.Condition())
        if parent is not None:
            parent.children.append(self)
        root = self
        while root.parent is not None:
            root = root.parent
        self._root = root
        if parent is None:
            self._seq = 0

    # -- selection (policy) ---------------------------------------------
    def _regen_cpu_locked(self) -> None:
        """Pay accumulated CPU debt back down at the configured
        generation rate (lazy: applied whenever eligibility is checked
        or usage is charged)."""
        import time as _time

        now = _time.monotonic()
        if self.cpu_quota_generation_s_per_s > 0 and self.cpu_usage_s > 0:
            self.cpu_usage_s = max(
                0.0, self.cpu_usage_s
                - (now - self._cpu_regen_at)
                * self.cpu_quota_generation_s_per_s)
        self._cpu_regen_at = now

    def _slot_free_locked(self) -> bool:
        if self.running >= self.hard_concurrency_limit:
            return False
        if (self.soft_memory_limit_bytes is not None
                and self.memory_usage > self.soft_memory_limit_bytes):
            return False
        if self.hard_cpu_limit_s is not None:
            self._regen_cpu_locked()
            if self.cpu_usage_s >= self.hard_cpu_limit_s:
                return False
        return True

    def _select_locked(self) -> Optional[_Ticket]:
        """The next ticket in this subtree eligible to run, or None."""
        if not self._slot_free_locked():
            return None
        ranked: List[Tuple[float, int, _Ticket]] = []
        if self._queue:
            t = self._queue[0]
            ranked.append((0.0, t.seq, t))
        for c in self.children:
            t = c._select_locked()
            if t is None:
                continue
            if self.scheduling_policy == "weighted_fair":
                # post-admission share: at equal running counts the
                # higher-weight group is the more under-served one
                key = (c.running + 1) / c.scheduling_weight
            elif self.scheduling_policy == "query_priority":
                key = 0.0        # strict FIFO: sequence decides
            else:                # fair
                key = float(c.running)
            ranked.append((key, t.seq, t))
        if not ranked:
            return None
        return min(ranked)[2]

    def acquire(self, timeout_s: Optional[float] = None,
                cancel_event: Optional[threading.Event] = None) -> None:
        """Block until this group's waiter is chosen by the root's policy
        walk AND every ancestor has a free slot; raise when the queue is
        full.  ``cancel_event`` makes the wait cancellable: when set
        (wake the waiter via :meth:`wake`), the ticket is dequeued
        without consuming a slot and ``QueryCancelledError`` raises —
        the queued-query DELETE path."""
        with self._cond:
            if cancel_event is not None and cancel_event.is_set():
                raise QueryCancelledError(
                    f"admission wait for {self.name!r} cancelled")
            root = self._root
            if self._chain_free_locked() and root._select_locked() is None:
                # capacity available and no eligible waiter to barge past
                self._grab_locked()
                return
            if self.queued >= self.max_queued:
                raise QueryQueueFullError(
                    f"Too many queued queries for {self.name!r}")
            root._seq += 1
            ticket = _Ticket(root._seq, self)
            self.queued += 1
            self._queue.append(ticket)
            try:
                ok = self._cond.wait_for(
                    lambda: ((cancel_event is not None
                              and cancel_event.is_set())
                             or (root._select_locked() is ticket
                                 and self._chain_free_locked())),
                    timeout=timeout_s)
                if cancel_event is not None and cancel_event.is_set():
                    raise QueryCancelledError(
                        f"admission wait for {self.name!r} cancelled")
                if not ok:
                    raise QueryQueueFullError(
                        f"queue wait timed out for {self.name!r}")
                self._queue.remove(ticket)
                self._grab_locked()
                # another slot may still be free for the next waiter
                self._cond.notify_all()
            finally:
                self.queued -= 1
                if ticket in self._queue:
                    self._queue.remove(ticket)
                # a removed waiter may unblock the policy walk for a
                # sibling (it can no longer be selected)
                self._cond.notify_all()

    def wake(self) -> None:
        """Wake every waiter on this group's tree (cancellation and
        CPU-quota regeneration are externally-timed eligibility
        changes the condition cannot observe by itself)."""
        with self._cond:
            self._cond.notify_all()

    def _chain_free_locked(self) -> bool:
        node: Optional[ResourceGroup] = self
        while node is not None:
            if not node._slot_free_locked():
                return False
            node = node.parent
        return True

    def _grab_locked(self) -> None:
        node: Optional[ResourceGroup] = self
        while node is not None:
            node.running += 1
            node = node.parent

    def release(self) -> None:
        with self._cond:
            node: Optional[ResourceGroup] = self
            while node is not None:
                node.running -= 1
                node = node.parent
            self._cond.notify_all()

    def set_memory_usage(self, bytes_: int) -> None:
        """Feed tracked memory (ClusterMemoryManager assigns query memory
        to groups); crossing below the soft limit wakes waiters."""
        with self._cond:
            self.memory_usage = bytes_
            self._cond.notify_all()

    def charge_cpu(self, seconds: float) -> None:
        """Charge a completed query's execution seconds to this group
        and every ancestor (the cpuUsageMillis accounting); the next
        eligibility check regenerates at the configured rate."""
        with self._cond:
            node: Optional[ResourceGroup] = self
            while node is not None:
                node._regen_cpu_locked()
                node.cpu_usage_s += max(float(seconds), 0.0)
                node = node.parent

    def stats_locked_snapshot(self) -> Dict[str, Any]:
        """One group's admission counters (the /metrics and
        system.runtime surface)."""
        with self._cond:
            return {"name": self.name, "running": self.running,
                    "queued": self.queued,
                    "hard_concurrency_limit": self.hard_concurrency_limit,
                    "max_queued": self.max_queued,
                    "cpu_usage_s": round(self.cpu_usage_s, 3),
                    "memory_usage_bytes": self.memory_usage}


class ResourceGroupManager:
    """Selects the group for a session (the rule-based selector role:
    per-user groups under a root)."""

    def __init__(self, hard_concurrency_limit: int = 16,
                 max_queued: int = 64, per_user_limit: int = 8,
                 scheduling_policy: str = "fair"):
        self.root = ResourceGroup("global", hard_concurrency_limit,
                                  max_queued,
                                  scheduling_policy=scheduling_policy)
        self.per_user_limit = per_user_limit
        self._groups: Dict[str, ResourceGroup] = {}
        self._lock = threading.Lock()

    def group_for(self, session: Session) -> ResourceGroup:
        with self._lock:
            g = self._groups.get(session.user)
            if g is None:
                g = ResourceGroup(f"global.{session.user}",
                                  self.per_user_limit,
                                  self.root.max_queued, parent=self.root)
                self._groups[session.user] = g
            return g

    def configure_group(self, user: str, **kwargs) -> ResourceGroup:
        """Pre-create / tune a user group (weight, soft memory limit,
        concurrency) — the DB/file-backed resource-group config role."""
        with self._lock:
            g = self._groups.get(user)
            if g is None:
                g = ResourceGroup(f"global.{user}", self.per_user_limit,
                                  self.root.max_queued, parent=self.root)
                self._groups[user] = g
        for k, v in kwargs.items():
            setattr(g, k, v)
        return g

    def update_memory_usage(self, per_user_bytes: Dict[str, int]) -> None:
        with self._lock:
            groups = dict(self._groups)
        for user, g in groups.items():
            g.set_memory_usage(per_user_bytes.get(user, 0))

    def stats(self) -> List[Dict[str, Any]]:
        """Admission counters for the root and every child group — the
        per-group queue-depth / running-count gauges the coordinator's
        /metrics plane renders."""
        with self._lock:
            groups = [self.root] + list(self._groups.values())
        return [g.stats_locked_snapshot() for g in groups]


# ---------------------------------------------------------------------------
# session property managers
# ---------------------------------------------------------------------------

class SessionPropertyManager:
    """Rule-based session property defaults
    (presto-session-property-managers role: the db/file-backed
    SessionPropertyConfigurationManager applies matching rules'
    properties to a session before execution; explicit SET SESSION
    values still win).

    Rules are ordered dicts: {"user": pattern, "source": pattern,
    "properties": {name: value}}; '*' wildcards; all matching rules
    apply, later rules overriding earlier ones."""

    def __init__(self, rules: List[Dict[str, Any]]):
        self.rules = list(rules)

    @staticmethod
    def _match(pattern: str, value: str) -> bool:
        import fnmatch

        return fnmatch.fnmatch(value, pattern)

    def defaults_for(self, user: str, source: str = "") -> Dict[str, str]:
        out: Dict[str, str] = {}
        for rule in self.rules:
            if not self._match(rule.get("user", "*"), user):
                continue
            if not self._match(rule.get("source", "*"), source):
                continue
            out.update(rule.get("properties", {}))
        return out

    def apply(self, session: "Session", source: str = "") -> None:
        """Set matched defaults that the session has not set itself."""
        for name, value in self.defaults_for(session.user,
                                             source).items():
            if name.lower() not in session.properties:
                session.set_property(name, str(value))
