"""Benchmark driver: suite-based macro benchmarks with warmup + stats.

Role model: presto-benchmark-driver (CLI suite runner, suite/query regex
selection) and presto-benchmark's AbstractBenchmark reporting
(rows/s + bytes/s per iteration, presto-benchmark/.../AbstractBenchmark
.java:76-100).  Suites here are named query dicts (the TPC-H and TPC-DS
files under tests/); results report wall-clock percentiles and output
rows/s per query.

    python -m presto_tpu.benchmark_driver --suite tpch --query 'q(1|6)' \
        --scale 0.01 --runs 3
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import re
import statistics
import time
from typing import Dict, List, Optional


@dataclasses.dataclass
class BenchResult:
    name: str
    runs: List[float]
    rows: int

    @property
    def median_s(self) -> float:
        return statistics.median(self.runs)

    @property
    def rows_per_s(self) -> float:
        return self.rows / self.median_s if self.median_s > 0 else 0.0

    def line(self) -> str:
        lo, hi = min(self.runs), max(self.runs)
        return (f"{self.name:<10} median {self.median_s:7.3f}s "
                f"[{lo:.3f}, {hi:.3f}] rows={self.rows} "
                f"({self.rows_per_s:,.0f} rows/s)")


def load_suite(suite: str) -> Dict[str, str]:
    if suite == "tpch":
        from tests.tpch_queries import QUERIES

        return {f"q{k}": v for k, v in QUERIES.items()}
    if suite == "tpcds":
        from tests.tpcds_queries import QUERIES as DS

        return {f"q{k}": v for k, v in DS.items()}
    raise SystemExit(f"unknown suite {suite!r} (tpch | tpcds)")


def run_suite(runner, queries: Dict[str, str], runs: int = 3,
              warmup: int = 1) -> List[BenchResult]:
    out = []
    for name, sql in queries.items():
        for _ in range(warmup):
            rows = len(runner.execute(sql).rows)
        walls = []
        for _ in range(runs):
            t0 = time.monotonic()
            rows = len(runner.execute(sql).rows)
            walls.append(time.monotonic() - t0)
        out.append(BenchResult(name, walls, rows))
    return out


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(prog="presto-tpu-benchmark-driver")
    p.add_argument("--suite", default="tpch")
    p.add_argument("--query", default=".*", help="query name regex")
    p.add_argument("--scale", type=float, default=0.01)
    p.add_argument("--runs", type=int, default=3)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    from presto_tpu.localrunner import LocalQueryRunner

    runner = LocalQueryRunner.tpch(scale=args.scale)
    pat = re.compile(args.query)
    queries = {n: q for n, q in load_suite(args.suite).items()
               if pat.fullmatch(n) or pat.search(n)}
    results = run_suite(runner, queries, args.runs, args.warmup)
    if args.json:
        print(json.dumps([
            {"name": r.name, "median_s": r.median_s, "rows": r.rows,
             "rows_per_s": r.rows_per_s, "runs": r.runs}
            for r in results]))
    else:
        for r in results:
            print(r.line())


if __name__ == "__main__":
    main()
