"""RowExpression -> executable closure (the ExpressionCompiler replacement).

The reference compiles RowExpressions to JVM bytecode PageProcessors
(presto-main/.../sql/gen/ExpressionCompiler.java:55,
PageFunctionCompiler.java:98).  Here compilation produces a Python closure
over the ``xp`` array namespace:

- run it with numpy       -> the interpreter / correctness oracle
  (the role H2 plays for the reference, SURVEY §4.2),
- trace it under jax.jit  -> the XLA/TPU path; XLA's fusion replaces the
  reference's hand-scheduled page loops, and the jit cache replaces the
  generated-class cache.

String expressions never execute on device: they are computed ONCE per
*dictionary entry* at compile time (dictionaries are compile-time constants
bound to the input schema) and become lookup-table gathers on device — the
generalization of the reference's DictionaryAwarePageProjection
(presto-main/.../operator/project/PageProcessor.java:54).

Null semantics are the SQL three-valued logic: each compiled node yields
``(values, valid)`` with ``valid=None`` meaning "no nulls" (the
Block.mayHaveNull fast path).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, Dictionary
from presto_tpu.expr import functions as F
from presto_tpu.expr.ir import (
    Call, Constant, InputRef, LambdaExpr, RowExpression, SpecialForm, VarRef,
)

Pair = Tuple[Any, Optional[Any]]  # (values, valid|None)


@dataclasses.dataclass
class CompiledExpr:
    """One compiled expression node graph.

    ``run(cols, n, xp)``: cols is the list of input-channel (values, valid)
    pairs, n the row count (only used when the expr has no inputs), xp the
    array namespace.  Returns (values, valid|None).
    """

    type: T.Type
    run: Callable[[Sequence[Pair], Any, Any], Pair]
    dictionary: Optional[Dictionary] = None   # set when type is string-ish
    const_str: Optional[str] = None           # set for string constants


def _and_valid(xp, a: Optional[Any], b: Optional[Any]) -> Optional[Any]:
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _filled(xp, values, valid, fill):
    if valid is None:
        return values
    return xp.where(valid, values, fill)


class ExprCompiler:
    def __init__(self, dictionaries: Dict[int, Dictionary],
                 vars: Optional[Dict[str, CompiledExpr]] = None):
        self.dicts = dictionaries
        self.vars = vars or {}

    def compile(self, expr: RowExpression) -> CompiledExpr:
        if isinstance(expr, InputRef):
            return self._input(expr)
        if isinstance(expr, Constant):
            return self._constant(expr)
        if isinstance(expr, VarRef):
            bound = self.vars.get(expr.name)
            if bound is None:
                raise ValueError(f"unbound lambda variable {expr.name}")
            return bound
        if isinstance(expr, LambdaExpr):
            raise ValueError("lambda outside an array/map function call")
        if isinstance(expr, Call):
            return self._call(expr)
        if isinstance(expr, SpecialForm):
            return self._special(expr)
        raise TypeError(f"unknown expression node: {expr!r}")

    # -- leaves ----------------------------------------------------------
    def _input(self, expr: InputRef) -> CompiledExpr:
        i = expr.index

        def run(cols, n, xp):
            return cols[i]

        d = self.dicts.get(i) if expr.type.is_dictionary else None
        if expr.type.is_dictionary and d is None:
            raise ValueError(f"no dictionary bound for string channel {i}")
        return CompiledExpr(expr.type, run, dictionary=d)

    def _constant(self, expr: Constant) -> CompiledExpr:
        t = expr.type
        if expr.value is None:
            if t.is_nested:
                def run(cols, n, xp):
                    from presto_tpu.batch import empty_column

                    nn = _rowcount(cols, n, xp)
                    return empty_column(t).pad(nn), np.zeros(nn, bool)

                return CompiledExpr(t, run)
            dt = t.np_dtype

            def run(cols, n, xp):
                nn = _rowcount(cols, n, xp)
                return xp.zeros(nn, dt), xp.zeros(nn, bool)

            d = Dictionary([""]) if t.is_dictionary else None
            return CompiledExpr(t, run, dictionary=d)
        if t.is_dictionary:
            s = str(expr.value)
            d = Dictionary([s])

            def run(cols, n, xp):
                return xp.zeros(_rowcount(cols, n, xp), np.int32), None

            return CompiledExpr(t, run, dictionary=d, const_str=s)
        value = expr.value
        dt = t.np_dtype

        def run(cols, n, xp):
            return xp.full(_rowcount(cols, n, xp), value, dt), None

        return CompiledExpr(t, run)

    # -- calls -----------------------------------------------------------
    def _call(self, expr: Call) -> CompiledExpr:
        fn: F.Scalar = expr.fn
        if fn is None:
            raise ValueError(f"unresolved call {expr.name}")
        if fn.kind == "nested":  # before arg compile: lambdas aren't exprs
            return self._nested_call(expr, fn)
        cargs = [self.compile(a) for a in expr.args]
        if fn.null_mode == "is_null":
            (a,) = cargs

            def run(cols, n, xp):
                v, valid = a.run(cols, n, xp)
                if valid is None:
                    return xp.zeros(_value_len(v), bool), None
                return ~valid, None

            return CompiledExpr(T.BOOLEAN, run)
        if fn.null_mode == "is_not_null":
            (a,) = cargs

            def run(cols, n, xp):
                v, valid = a.run(cols, n, xp)
                if valid is None:
                    return xp.ones(_value_len(v), bool), None
                return valid, None

            return CompiledExpr(T.BOOLEAN, run)
        if fn.null_mode == "hash64":
            types = [a.type for a in expr.args]

            def run(cols, n, xp):
                from presto_tpu.ops.hashing import row_hash

                triples = []
                for c, ty in zip(cargs, types):
                    v, valid = c.run(cols, n, xp)
                    triples.append((v, valid, ty))
                return row_hash(triples).astype("int64"), None

            return CompiledExpr(T.BIGINT, run)
        if fn.kind == "string":
            return self._string_call(expr, fn, cargs)
        impl = fn.impl
        if fn.null_mode == "custom_divzero":
            a, b = cargs

            def run(cols, n, xp):
                av, avalid = a.run(cols, n, xp)
                bv, bvalid = b.run(cols, n, xp)
                nonzero = bv != 0
                safe_b = xp.where(nonzero, bv, bv.dtype.type(1))
                out = impl(xp, av, safe_b)
                valid = _and_valid(xp, _and_valid(xp, avalid, bvalid), nonzero)
                return out, valid

            return CompiledExpr(fn.result_type, run)

        def run(cols, n, xp):
            vals = []
            valid = None
            for c in cargs:
                v, cv = c.run(cols, n, xp)
                vals.append(v)
                valid = _and_valid(xp, valid, cv)
            return impl(xp, *vals), valid

        return CompiledExpr(fn.result_type, run)

    def _nested_call(self, expr: Call, fn: F.Scalar) -> CompiledExpr:
        """Array/map/row functions: host-side over offsets + flat children.

        Lambda arguments become runtime body evaluators over the flattened
        element domain; outer captures are repeated per element (the
        ArrayTransformFunction shape, presto-main/.../operator/scalar/).
        """
        value_nodes = [a for a in expr.args
                       if not isinstance(a, LambdaExpr)]
        lambda_nodes = [a for a in expr.args if isinstance(a, LambdaExpr)]
        cvals = [self.compile(a) for a in value_nodes]
        impl = fn.impl
        rt = fn.result_type
        out_dict = getattr(fn, "out_dictionary", None)
        compiler = self

        def as_arg(c: CompiledExpr, v):
            if isinstance(v, Column):
                return v
            if c.const_str is not None:
                return c.const_str
            if c.type.is_dictionary:
                return Column(c.type, np.asarray(v), None, c.dictionary)
            return np.asarray(v)

        def run(cols, n, xp):
            # nested evaluation is host-side by design (strings/offsets);
            # heavy flat-child math still vectorizes through numpy/XLA-cpu
            host_cols = [(_host_value(v), None if valid is None
                          else np.asarray(valid)) for v, valid in cols]
            args, valids = [], []
            for c in cvals:
                v, valid = c.run(host_cols, n, np)
                args.append(as_arg(c, v))
                valids.append(valid)
            if lambda_nodes:
                lambdas = [
                    _LambdaEvaluator(lam, compiler, host_cols, n)
                    for lam in lambda_nodes]
                return impl(args, valids, n, np, lambdas=lambdas)
            return impl(args, valids, n, np)

        return CompiledExpr(rt, run, dictionary=out_dict)

    def _string_call(self, expr: Call, fn: F.Scalar,
                     cargs: List[CompiledExpr]) -> CompiledExpr:
        """Host-side per-dictionary-entry evaluation, device gather."""
        # Identify the (single) dictionary-column argument; all others must
        # be constants.
        dict_arg_idx = None
        const_vals: List[Any] = []
        for i, (ca, node) in enumerate(zip(cargs, expr.args)):
            if ca.const_str is not None:
                const_vals.append(ca.const_str)
            elif isinstance(node, Constant):
                const_vals.append(node.value)
            elif ca.type.is_dictionary:
                if dict_arg_idx is not None or ca.dictionary is None:
                    # several string columns, or a runtime-built dictionary
                    # (cast-to-varchar / array_join): evaluate row-wise on
                    # the host instead of per-dictionary-entry
                    return self._string_host_call(fn, cargs)
                dict_arg_idx = i
                const_vals.append(None)
            else:
                # non-constant non-string argument (e.g. strpos(s, col)):
                # host row-wise fallback
                return self._string_host_call(fn, cargs)
        if dict_arg_idx is None:
            # all-constant: fold at compile time
            result = fn.impl(*const_vals)
            return self._constant(Constant(result, fn.result_type))
        src = cargs[dict_arg_idx]
        entries = src.dictionary.values
        per_entry = []
        for e in entries:
            args = list(const_vals)
            args[dict_arg_idx] = e
            per_entry.append(fn.impl(*args))
        rt = fn.result_type
        # a None per-entry result means NULL for rows holding that code
        # (split_part past the end, regexp_extract without a match, ...)
        null_codes = [i for i, v in enumerate(per_entry) if v is None]
        ok_np = None
        if null_codes:
            ok_np = np.ones(len(per_entry), dtype=bool)
            ok_np[null_codes] = False
        if rt.is_dictionary:
            # intern (dedupe) results: equal strings MUST share a code —
            # group-by/join/compare on dictionary columns operate on codes
            # (e.g. substr over a per-row-distinct phone column yields few
            # distinct country codes from many entries)
            out_dict = Dictionary()
            remap_np = np.empty(max(len(per_entry), 1), np.int32)
            for i, v in enumerate(per_entry):
                remap_np[i] = out_dict.intern(v if v is not None else "")

            def run(cols, n, xp):
                codes, valid = src.run(cols, n, xp)
                if ok_np is not None:
                    ok = xp.take(xp.asarray(ok_np), codes, axis=0)
                    valid = ok if valid is None else (valid & ok)
                codes = xp.take(xp.asarray(remap_np), codes, axis=0)
                return codes, valid

            return CompiledExpr(rt, run, dictionary=out_dict)
        lookup_np = np.asarray(
            [v if v is not None else 0 for v in per_entry],
            dtype=rt.np_dtype)

        def run(cols, n, xp):
            codes, valid = src.run(cols, n, xp)
            table = xp.asarray(lookup_np)
            out = xp.take(table, codes, axis=0)
            if ok_np is not None:
                ok = xp.take(xp.asarray(ok_np), codes, axis=0)
                valid = ok if valid is None else (valid & ok)
            return out, valid

        return CompiledExpr(rt, run)

    def _string_host_call(self, fn: F.Scalar,
                          cargs: List[CompiledExpr]) -> CompiledExpr:
        """Row-wise host evaluation of a string function (used when the
        per-dictionary-entry binding can't apply: several string columns,
        runtime dictionaries, or non-constant non-string arguments).
        Results intern into a per-call-site append-only dictionary."""
        rt = fn.result_type
        impl = fn.impl
        out_dict = Dictionary() if rt.is_dictionary else None

        def decode(c: CompiledExpr, v, valid, n):
            if isinstance(v, Column):
                return v.to_pylist(n) if v.type.is_dictionary \
                    or v.type.is_nested else list(np.asarray(v.values)[:n])
            if c.const_str is not None:
                return [c.const_str] * n
            v = np.asarray(v)
            if c.type.is_dictionary:
                d = c.dictionary
                return [d.values[int(x)] if 0 <= int(x) < len(d) else None
                        for x in v[:n]]
            return [c.type.to_python(x) for x in v[:n]]

        def run(cols, n, xp):
            host_cols = [(_host_value(v), None if valid is None
                          else np.asarray(valid)) for v, valid in cols]
            nn = _rowcount(host_cols, n, np)
            arg_lists = []
            valid_all = None
            for c in cargs:
                v, valid = c.run(host_cols, nn, np)
                arg_lists.append(decode(c, v, valid, nn))
                valid_all = _and_valid(np, valid_all,
                                       None if valid is None
                                       else np.asarray(valid))
            live = np.ones(nn, bool) if valid_all is None else valid_all
            ok = live.copy()
            if out_dict is not None:
                out = np.zeros(nn, np.int32)
            else:
                out = np.zeros(nn, rt.np_dtype)
            for i in range(nn):
                if not live[i]:
                    continue
                res = impl(*(al[i] for al in arg_lists))
                if res is None:
                    ok[i] = False
                elif out_dict is not None:
                    out[i] = out_dict.intern(res)
                else:
                    out[i] = res
            valid = None if bool(ok.all()) else ok
            return out, valid

        return CompiledExpr(rt, run, dictionary=out_dict)

    # -- special forms ---------------------------------------------------
    def _special(self, expr: SpecialForm) -> CompiledExpr:
        form = expr.form
        if form == "AND" or form == "OR":
            a, b = (self.compile(x) for x in expr.args)
            is_and = form == "AND"

            def run(cols, n, xp):
                av, avalid = a.run(cols, n, xp)
                bv, bvalid = b.run(cols, n, xp)
                fill = is_and  # AND fills nulls True; OR fills False
                af = _filled(xp, av, avalid, fill)
                bf = _filled(xp, bv, bvalid, fill)
                out = (af & bf) if is_and else (af | bf)
                if avalid is None and bvalid is None:
                    return out, None
                ones = xp.ones(af.shape[0], bool)
                avl = avalid if avalid is not None else ones
                bvl = bvalid if bvalid is not None else ones
                if is_and:
                    known = (avl & ~af) | (bvl & ~bf)
                else:
                    known = (avl & af) | (bvl & bf)
                return out, (avl & bvl) | known

            return CompiledExpr(T.BOOLEAN, run)
        if form == "IF":
            cond, then, other = (self.compile(x) for x in expr.args)
            return self._if(expr.type, cond, then, other)
        if form == "SWITCH":
            # args = [default, cond1, v1, cond2, v2, ...] -> nested IFs
            default = expr.args[0]
            pairs = list(zip(expr.args[1::2], expr.args[2::2]))
            node: RowExpression = default
            for cond, val in reversed(pairs):
                node = SpecialForm("IF", (cond, val, node), expr.type)
            return self.compile(node)
        if form == "COALESCE":
            cargs = [self.compile(a) for a in expr.args]
            return self._coalesce(expr.type, cargs)
        if form == "IN":
            return self._in(expr)
        raise ValueError(f"unknown special form {form}")

    def _if(self, rt: T.Type, cond: CompiledExpr, then: CompiledExpr,
            other: CompiledExpr) -> CompiledExpr:
        out_dict = None
        remap_then = remap_other = None
        if rt.is_dictionary:
            out_dict = Dictionary()
            remap_then = then.dictionary.remap_into(out_dict)
            remap_other = other.dictionary.remap_into(out_dict)

        def run(cols, n, xp):
            cv, cvalid = cond.run(cols, n, xp)
            tv, tvalid = then.run(cols, n, xp)
            ov, ovalid = other.run(cols, n, xp)
            take_then = _filled(xp, cv, cvalid, False)
            if remap_then is not None:
                tv = xp.take(xp.asarray(remap_then), tv, axis=0)
                ov = xp.take(xp.asarray(remap_other), ov, axis=0)
            out = xp.where(take_then, tv, ov)
            if tvalid is None and ovalid is None:
                return out, None
            ones = xp.ones(out.shape[0], bool)
            tvl = tvalid if tvalid is not None else ones
            ovl = ovalid if ovalid is not None else ones
            return out, xp.where(take_then, tvl, ovl)

        return CompiledExpr(rt, run, dictionary=out_dict)

    def _coalesce(self, rt: T.Type, cargs: List[CompiledExpr]) -> CompiledExpr:
        out_dict = None
        remaps = None
        if rt.is_dictionary:
            out_dict = Dictionary()
            remaps = [c.dictionary.remap_into(out_dict) for c in cargs]

        def run(cols, n, xp):
            acc_v = acc_valid = None
            for i, c in enumerate(cargs):
                v, valid = c.run(cols, n, xp)
                if remaps is not None:
                    v = xp.take(xp.asarray(remaps[i]), v, axis=0)
                if acc_v is None:
                    acc_v, acc_valid = v, valid
                else:
                    need = ~acc_valid  # positions still null
                    acc_v = xp.where(need, v, acc_v)
                    if valid is None:
                        acc_valid = None
                    else:
                        acc_valid = acc_valid | valid
                if acc_valid is None:
                    break
            return acc_v, acc_valid

        return CompiledExpr(rt, run, dictionary=out_dict)

    def _in(self, expr: SpecialForm) -> CompiledExpr:
        value = self.compile(expr.args[0])
        items = expr.args[1:]
        if value.type.is_dictionary:
            if not all(isinstance(i, Constant) and i.value is not None
                       for i in items):
                raise NotImplementedError("IN over non-constant string list")
            # a set: duplicate literals in the IN list are legal SQL
            consts = {str(i.value) for i in items}
            lookup_np = np.asarray(
                [e in consts for e in value.dictionary.values], dtype=bool)

            def run(cols, n, xp):
                codes, valid = value.run(cols, n, xp)
                return xp.take(xp.asarray(lookup_np), codes, axis=0), valid

            return CompiledExpr(T.BOOLEAN, run)
        citems = [self.compile(i) for i in items]

        def run(cols, n, xp):
            v, valid = value.run(cols, n, xp)
            out = None
            for ci in citems:
                iv, ivalid = ci.run(cols, n, xp)
                valid = _and_valid(xp, valid, ivalid)
                eq = v == iv
                out = eq if out is None else (out | eq)
            return out, valid

        return CompiledExpr(T.BOOLEAN, run)


def _value_len(v) -> int:
    return v.values.shape[0] if isinstance(v, Column) else v.shape[0]


def _rowcount(cols, n, xp):
    for v, _ in cols:
        return _value_len(v)
    return n


def _host_value(v):
    if isinstance(v, Column):
        return v.to_numpy()
    return np.asarray(v)


class _LambdaEvaluator:
    """Runtime evaluator for a lambda body over flattened elements.

    ``__call__(child_cols, row_of, total)``: child_cols are the parameter
    bindings (host Columns aligned to the flat element domain), row_of maps
    each element to its parent row (for repeating outer captures), total is
    the element count.  Returns the body's (values, valid).
    """

    def __init__(self, lam: LambdaExpr, outer: "ExprCompiler",
                 outer_cols, n: int):
        self.lam = lam
        self.outer = outer
        self.outer_cols = outer_cols
        self.n = n

    def __call__(self, child_cols, row_of, total):
        lam = self.lam
        vars: Dict[str, CompiledExpr] = dict(self.outer.vars)
        for name, ptyp, ccol in zip(lam.params, lam.param_types, child_cols):
            pair = _child_pair(ccol)
            d = ccol.dictionary if ptyp.is_dictionary else None

            def make_run(p):
                return lambda cols, n, xp: p

            vars[name] = CompiledExpr(ptyp, make_run(pair), dictionary=d)
        # outer captures: repeat per element
        expanded = []
        for v, valid in self.outer_cols:
            if isinstance(v, Column):
                ev = v.take(row_of)
            else:
                ev = np.asarray(v)[row_of]
            evalid = None if valid is None else np.asarray(valid)[row_of]
            expanded.append((ev, evalid))
        sub = ExprCompiler(self.outer.dicts, vars=vars)
        compiled = sub.compile(lam.body)
        return compiled.run(expanded, total, np)


def _child_pair(ccol: Column):
    """A child Column as a (values, valid) pair for the body compiler."""
    valid = None if ccol.valid is None else np.asarray(ccol.valid)
    if ccol.type.is_nested:
        return (ccol.with_values(ccol.values, None), valid)
    return (np.asarray(ccol.values), valid)


# ---------------------------------------------------------------------------
# Convenience entry points
# ---------------------------------------------------------------------------

def compile_expr(expr: RowExpression,
                 dictionaries: Optional[Dict[int, Dictionary]] = None
                 ) -> CompiledExpr:
    return ExprCompiler(dictionaries or {}).compile(expr)


def batch_dictionaries(batch: Batch) -> Dict[int, Dictionary]:
    return {i: c.dictionary for i, c in enumerate(batch.columns)
            if c.dictionary is not None}


def needs_host_path(exprs: Sequence[RowExpression]) -> bool:
    """True when any expression touches nested types: those evaluate
    host-side (offset bookkeeping + flat-child math), so the enclosing
    operator must not jit-trace the column arrays."""
    from presto_tpu.expr.ir import walk

    for expr in exprs:
        if expr is None:
            continue
        for e in walk(expr):
            ty = getattr(e, "type", None)
            if ty is not None and T.is_nested(ty):
                return True
            fn = getattr(e, "fn", None)
            if fn is None:
                continue
            if getattr(fn, "kind", None) == "nested":
                return True
            if getattr(fn, "kind", None) == "string":
                # row-wise host fallback cases (see _string_call)
                str_cols = sum(
                    1 for a in e.args
                    if a.type.is_dictionary and not isinstance(a, Constant))
                other_nonconst = any(
                    not a.type.is_dictionary and not isinstance(a, Constant)
                    for a in e.args)
                if str_cols > 1 or other_nonconst:
                    return True
    return False


def batch_pairs(batch: Batch) -> List[Pair]:
    """Input-channel pairs for compiled expressions (nested as Columns)."""
    cols: List[Pair] = []
    for c in batch.columns:
        if c.type.is_nested:
            nc = c.to_numpy()
            cols.append((Column(nc.type, nc.values, None, nc.dictionary,
                                nc.children), nc.valid))
        else:
            cols.append((c.values, c.valid))
    return cols


def result_column(compiled: CompiledExpr, values, valid) -> Column:
    if isinstance(values, Column):
        return Column(values.type, values.values, valid,
                      values.dictionary, values.children)
    return Column(compiled.type, values, valid, compiled.dictionary)


def evaluate(expr: RowExpression, batch: Batch, xp=np) -> Column:
    """Interpret one expression over a Batch (the oracle path)."""
    compiled = compile_expr(expr, batch_dictionaries(batch))
    values, valid = compiled.run(batch_pairs(batch), batch.num_rows, xp)
    return result_column(compiled, values, valid)
