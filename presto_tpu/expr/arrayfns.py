"""Array/map/row function implementations (host-side).

The reference's array/map/lambda library lives in
presto-main/.../operator/scalar/ (ArrayTransformFunction, MapKeys,
ArrayDistinctFunction, ...).  Nested values here are host Columns
(lengths + flattened children, batch.py); functions manipulate offsets
host-side with vectorized numpy, and lambda bodies evaluate over the
*flattened* child arrays — so ``transform(arr, x -> f(x))`` is one
elementwise pass over the flat element vector (the TPU-friendly shape:
no per-row loops; ragged structure only touches offset arithmetic).

Calling convention (compile.py ``kind == "nested"``):
``impl(args, valids, n, xp) -> (values, valid|None)`` where each arg is

- a host Column for nested- and string-typed inputs (string Columns carry
  their Dictionary; code comparisons always decode),
- a Python scalar for compile-time constants,
- a numpy array otherwise,

and nested/string results are returned as Columns (string results intern
into a per-call-site append-only Dictionary so codes stay stable across
batches).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import (
    Column, Dictionary, _range_gather_indices, column_from_pylist,
    _concat_columns,
)

Pair = Tuple[Any, Optional[np.ndarray]]


def _lengths(col: Column) -> np.ndarray:
    return np.asarray(col.values, np.int64)


def _offsets(col: Column) -> np.ndarray:
    return np.concatenate([np.zeros(1, np.int64),
                           np.cumsum(_lengths(col), dtype=np.int64)])


def _rebuild(typ: T.Type, lengths: np.ndarray, kids: List[Column]) -> Column:
    return Column(typ, np.asarray(lengths, np.int32), None, None, tuple(kids))


def _row_ids(lengths: np.ndarray) -> np.ndarray:
    """Flat-element -> parent-row index."""
    return np.repeat(np.arange(lengths.shape[0], dtype=np.int64), lengths)


def _and_all(*valids) -> Optional[np.ndarray]:
    out = None
    for v in valids:
        if v is not None:
            out = v if out is None else out & v
    return out


def _decoded(col: Column) -> np.ndarray:
    """Column values in comparable form (strings decoded to objects)."""
    kv = np.asarray(col.values)
    if col.type.is_dictionary:
        if len(col.dictionary) == 0:
            return np.zeros(kv.shape[0], object)
        return np.asarray(col.dictionary.values, dtype=object)[kv]
    return kv


def _needle_values(needle, n: int):
    """Per-row comparable values for the searched element."""
    if isinstance(needle, Column):
        return _decoded(needle)
    if isinstance(needle, np.ndarray):
        return needle
    return np.broadcast_to(np.asarray(needle, dtype=object if
                                      isinstance(needle, str) else None), (n,))


def _compare_values(kid: Column, needle, n: int,
                    row_of: np.ndarray) -> np.ndarray:
    """elementwise kid[i] == needle[row_of[i]] (NULL compares unequal)."""
    kv = _decoded(kid)
    nv = _needle_values(needle, n)
    eq = kv == nv[row_of]
    if kid.valid is not None:
        eq = eq & np.asarray(kid.valid)
    return eq


def _take_kid(kid: Column, idx: np.ndarray) -> Column:
    if idx.shape[0] == 0:
        return kid.head(0)
    return kid.take(idx)


def _kid_result(kid: Column, n: int) -> Any:
    """A child column as a nested-call result value."""
    if kid.type.is_nested or kid.type.is_dictionary:
        return kid
    return kid.values


# ---------------------------------------------------------------------------
# basics
# ---------------------------------------------------------------------------

def cardinality(args, valids, n, xp) -> Pair:
    (col,) = args
    return _lengths(col).astype(np.int64), _and_all(*valids)


def array_subscript(args, valids, n, xp) -> Pair:
    """arr[i] / element_at(arr, i): 1-based; negative = from end;
    out-of-range yields NULL (element_at semantics)."""
    col, idx = args
    lengths = _lengths(col)
    offsets = _offsets(col)
    idx = np.broadcast_to(np.asarray(idx, np.int64), (n,))
    pos = np.where(idx < 0, lengths + idx, idx - 1)  # 0-based
    ok = (pos >= 0) & (pos < lengths)
    safe = np.where(ok, offsets[:-1] + np.clip(pos, 0, None), 0)
    kid = col.children[0]
    if kid.values.shape[0] == 0:
        from presto_tpu.batch import empty_column

        return _kid_result(empty_column(kid.type).pad(n), n), \
            np.zeros(n, bool)
    taken = kid.take(np.clip(safe, 0, kid.values.shape[0] - 1))
    if taken.valid is not None:
        ok = ok & np.asarray(taken.valid)
    valid = _and_all(ok, *valids)
    return _kid_result(taken.with_values(taken.values, None), n), valid


def map_subscript(args, valids, n, xp) -> Pair:
    """m[k] / element_at(m, k): NULL when the key is absent."""
    col, key = args
    lengths = _lengths(col)
    row_of = _row_ids(lengths)
    eq = _compare_values(col.children[0], key, n, row_of)
    hit_rows = row_of[eq]
    hit_pos = np.nonzero(eq)[0]
    sel = np.zeros(n, np.int64)
    found = np.zeros(n, bool)
    sel[hit_rows] = hit_pos      # duplicate keys: last wins
    found[hit_rows] = True
    vcol = col.children[1]
    if vcol.values.shape[0] == 0:
        from presto_tpu.batch import empty_column

        return _kid_result(empty_column(vcol.type).pad(n), n), \
            np.zeros(n, bool)
    taken = vcol.take(np.clip(sel, 0, vcol.values.shape[0] - 1))
    if taken.valid is not None:
        found = found & np.asarray(taken.valid)
    valid = _and_all(found, *valids)
    return _kid_result(taken.with_values(taken.values, None), n), valid


def contains(args, valids, n, xp) -> Pair:
    col, needle = args
    row_of = _row_ids(_lengths(col))
    eq = _compare_values(col.children[0], needle, n, row_of)
    out = np.zeros(n, bool)
    np.logical_or.at(out, row_of, eq)
    return out, _and_all(*valids)


def array_position(args, valids, n, xp) -> Pair:
    col, needle = args
    lengths = _lengths(col)
    offsets = _offsets(col)
    row_of = _row_ids(lengths)
    eq = _compare_values(col.children[0], needle, n, row_of)
    out = np.zeros(n, np.int64)
    idx = np.nonzero(eq)[0][::-1]          # reverse so first match wins
    rows = row_of[idx]
    out[rows] = idx - offsets[rows] + 1    # 1-based; 0 when absent
    return out, _and_all(*valids)


def _minmax(col: Column, mode: str, n: int) -> Pair:
    lengths = _lengths(col)
    row_of = _row_ids(lengths)
    kid = col.children[0]
    kv = np.asarray(kid.values)
    if kid.type.is_dictionary and len(kid.dictionary):
        keyv = kid.dictionary.sort_ranks()[kv]
    else:
        keyv = kv
    live = np.ones(kv.shape[0], bool) if kid.valid is None \
        else np.asarray(kid.valid)
    # a NULL element makes the result NULL (Presto array_min/max)
    has_null_elem = np.zeros(n, bool)
    np.logical_or.at(has_null_elem, row_of, ~live)
    nonempty = lengths > 0
    if kv.shape[0] == 0:
        from presto_tpu.batch import empty_column

        return _kid_result(empty_column(kid.type).pad(n), n), \
            np.zeros(n, bool)
    order = np.argsort(keyv, kind="stable")
    if mode == "max":
        order = order[::-1]
    best = np.zeros(n, np.int64)
    best[row_of[order[::-1]]] = order[::-1]   # best element wins last write
    taken = kid.take(best)
    valid = nonempty & ~has_null_elem
    return _kid_result(taken.with_values(taken.values, None), n), valid


def array_min(args, valids, n, xp) -> Pair:
    out, valid = _minmax(args[0], "min", n)
    return out, _and_all(valid, *valids)


def array_max(args, valids, n, xp) -> Pair:
    out, valid = _minmax(args[0], "max", n)
    return out, _and_all(valid, *valids)


# ---------------------------------------------------------------------------
# restructuring
# ---------------------------------------------------------------------------

def array_concat(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        cols = list(args)
        lengths = sum(_lengths(c) for c in cols)
        kids, order_rows = [], []
        for c in cols:
            ln = _lengths(c)
            idx = _range_gather_indices(_offsets(c)[:-1], ln)
            kids.append(_take_kid(c.children[0], idx))
            order_rows.append(np.repeat(np.arange(n), ln))
        flat = _concat_columns(kids, [k.values.shape[0] for k in kids]) \
            if len(kids) > 1 else kids[0]
        rows_cat = np.concatenate(order_rows)
        # stable sort by row groups each row's elements, inputs in arg order
        flat = _take_kid(flat, np.argsort(rows_cat, kind="stable"))
        return _rebuild(typ, lengths, [flat]), _and_all(*valids)

    return impl


def flatten(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        inner = col.children[0]            # array(E) column, flattened
        outer_lengths = _lengths(col)
        inner_lengths = _lengths(inner)
        row_of_inner = _row_ids(outer_lengths)
        out_lengths = np.zeros(n, np.int64)
        np.add.at(out_lengths, row_of_inner, inner_lengths)
        # elements are already stored in row-major order
        return _rebuild(typ, out_lengths, [inner.children[0]]), \
            _and_all(*valids)

    return impl


def array_reverse(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        lengths = _lengths(col)
        offsets = _offsets(col)
        total = int(offsets[-1])
        ramp = np.arange(total, dtype=np.int64)
        row_of = _row_ids(lengths)
        within = ramp - offsets[row_of]
        rev_idx = offsets[row_of] + (lengths[row_of] - 1 - within)
        kid = _take_kid(col.children[0], rev_idx)
        return _rebuild(typ, lengths, [kid]), _and_all(*valids)

    return impl


def array_distinct(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        lengths = _lengths(col)
        row_of = _row_ids(lengths)
        kid = col.children[0]
        vals = _decoded(kid)
        live = np.ones(vals.shape[0], bool) if kid.valid is None \
            else np.asarray(kid.valid)
        seen = set()
        keep = np.ones(vals.shape[0], bool)
        for i in range(vals.shape[0]):
            key = (int(row_of[i]), vals[i] if live[i] else None,
                   bool(live[i]))
            if key in seen:
                keep[i] = False
            else:
                seen.add(key)
        new_lengths = np.zeros(n, np.int64)
        np.add.at(new_lengths, row_of[keep], 1)
        kid2 = _take_kid(kid, np.nonzero(keep)[0])
        return _rebuild(typ, new_lengths, [kid2]), _and_all(*valids)

    return impl


def array_sort(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        lengths = _lengths(col)
        row_of = _row_ids(lengths)
        kid = col.children[0]
        kv = np.asarray(kid.values)
        if kid.type.is_dictionary and len(kid.dictionary):
            keyv = kid.dictionary.sort_ranks()[kv]
        else:
            keyv = kv
        live = np.ones(kv.shape[0], bool) if kid.valid is None \
            else np.asarray(kid.valid)
        # NULLS LAST within each row (Presto array_sort)
        order = np.lexsort((keyv, ~live, row_of))
        return _rebuild(typ, lengths, [_take_kid(kid, order)]), \
            _and_all(*valids)

    return impl


def slice_fn(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        col, start, length = args
        lengths = _lengths(col)
        offsets = _offsets(col)
        start = np.broadcast_to(np.asarray(start, np.int64), (n,))
        length = np.clip(
            np.broadcast_to(np.asarray(length, np.int64), (n,)), 0, None)
        begin0 = np.where(start > 0, start - 1, lengths + start)  # 1-based
        begin0 = np.clip(begin0, 0, lengths)
        count = np.clip(length, 0, lengths - begin0)
        idx = _range_gather_indices(offsets[:-1] + begin0, count)
        kid = _take_kid(col.children[0], idx)
        return _rebuild(typ, count, [kid]), _and_all(*valids)

    return impl


def array_remove(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        col, needle = args
        lengths = _lengths(col)
        row_of = _row_ids(lengths)
        eq = _compare_values(col.children[0], needle, n, row_of)
        keep = ~eq
        new_lengths = np.zeros(n, np.int64)
        np.add.at(new_lengths, row_of[keep], 1)
        kid = _take_kid(col.children[0], np.nonzero(keep)[0])
        return _rebuild(typ, new_lengths, [kid]), _and_all(*valids)

    return impl


def set_op(typ: T.Type, mode: str):
    """array_intersect / array_union / array_except (distinct results)."""

    def impl(args, valids, n, xp) -> Pair:
        per_row: List[List[set]] = []
        for col in args:
            acc = [set() for _ in range(n)]
            lengths = _lengths(col)
            row_of = _row_ids(lengths)
            vals = col.children[0].to_pylist(int(lengths.sum()))
            for i, v in zip(row_of, vals):
                acc[i].add(v)
            per_row.append(acc)
        a_rows, b_rows = per_row
        out: List[Any] = []
        for i in range(n):
            if mode == "intersect":
                s = a_rows[i] & b_rows[i]
            elif mode == "union":
                s = a_rows[i] | b_rows[i]
            else:
                s = a_rows[i] - b_rows[i]
            out.append(sorted(s, key=lambda v: (v is None, str(v))))
        col = column_from_pylist(typ, out)
        return Column(typ, col.values, None, None, col.children), \
            _and_all(*valids)

    return impl


def arrays_overlap():
    def impl(args, valids, n, xp) -> Pair:
        a, b = args
        out = np.zeros(n, bool)
        sets_a = [set() for _ in range(n)]
        la = _lengths(a)
        vals_a = a.children[0].to_pylist(int(la.sum()))
        for i, v in zip(_row_ids(la), vals_a):
            if v is not None:
                sets_a[i].add(v)
        lb = _lengths(b)
        vals_b = b.children[0].to_pylist(int(lb.sum()))
        for i, v in zip(_row_ids(lb), vals_b):
            if v is not None and v in sets_a[i]:
                out[i] = True
        return out, _and_all(*valids)

    return impl


def repeat_fn(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        elem, count = args
        count = np.clip(
            np.broadcast_to(np.asarray(count, np.int64), (n,)), 0, None)
        idx = np.repeat(np.arange(n, dtype=np.int64), count)
        elem_valid = valids[0]
        if isinstance(elem, Column):
            kid = _take_kid(elem, idx)
            if elem_valid is not None:
                ev = np.asarray(elem_valid)[idx]
                kid = kid.with_values(
                    kid.values,
                    ev if kid.valid is None else np.asarray(kid.valid) & ev)
        else:
            ev = np.broadcast_to(np.asarray(elem), (n,))
            kid_valid = None if elem_valid is None \
                else np.asarray(elem_valid)[idx]
            if typ.element.is_dictionary:
                d = Dictionary()
                codes = np.asarray(
                    [d.intern(str(v)) for v in ev[idx]], np.int32) \
                    if idx.shape[0] else np.zeros(0, np.int32)
                kid = Column(typ.element, codes, kid_valid, d)
            else:
                kid = Column(typ.element,
                             np.asarray(ev[idx], typ.element.np_dtype),
                             kid_valid)
        return _rebuild(typ, count, [kid]), valids[1]

    return impl


def sequence_fn(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        start = np.broadcast_to(np.asarray(args[0], np.int64), (n,))
        stop = np.broadcast_to(np.asarray(args[1], np.int64), (n,))
        if len(args) > 2:
            step = np.broadcast_to(np.asarray(args[2], np.int64), (n,))
        else:
            step = np.where(stop >= start, 1, -1).astype(np.int64)
        count = np.maximum((stop - start) // step + 1, 0)
        total = int(count.sum())
        flat_row = np.repeat(np.arange(n, dtype=np.int64), count)
        ends = np.cumsum(count)
        within = np.arange(total, dtype=np.int64) - \
            np.repeat(ends - count, count)
        flat = start[flat_row] + within * step[flat_row]
        return _rebuild(typ, count, [Column(T.BIGINT, flat)]), \
            _and_all(*valids)

    return impl


# ---------------------------------------------------------------------------
# strings <-> arrays
# ---------------------------------------------------------------------------

def array_join():
    out_dict = Dictionary()  # per call site; append-only => stable codes

    def impl(args, valids, n, xp) -> Pair:
        col = args[0]
        delim = args[1] if isinstance(args[1], str) else ""
        null_repl = args[2] if len(args) > 2 else None
        lengths = _lengths(col)
        row_of = _row_ids(lengths)
        vals = col.children[0].to_pylist(int(lengths.sum()))
        parts: List[List[str]] = [[] for _ in range(n)]
        for i, v in zip(row_of, vals):
            if v is None:
                if null_repl is not None:
                    parts[i].append(str(null_repl))
            else:
                parts[i].append(str(v))
        codes = np.asarray([out_dict.intern(delim.join(p)) for p in parts],
                           np.int32)
        return Column(T.VARCHAR, codes, None, out_dict), _and_all(*valids)

    return impl


def split_fn(typ: T.Type):
    """split(string, delim [, limit]) -> array(varchar)."""

    def impl(args, valids, n, xp) -> Pair:
        src = args[0]
        delim = args[1]
        limit = None if len(args) < 3 else int(np.asarray(args[2]).flat[0])

        def split_one(s: str) -> List[str]:
            return s.split(delim) if limit is None \
                else s.split(delim, limit - 1)

        if isinstance(src, str):       # constant input
            lists = [split_one(src)] * n
        else:
            per_entry = {}
            codes = np.asarray(src.values)
            dvals = src.dictionary.values
            lists = []
            for c in codes:
                c = int(c)
                if c not in per_entry:
                    per_entry[c] = split_one(dvals[c]) \
                        if 0 <= c < len(dvals) else []
                lists.append(per_entry[c])
        col = column_from_pylist(typ, lists)
        return Column(typ, col.values, None, None, col.children), \
            _and_all(*valids)

    return impl


# ---------------------------------------------------------------------------
# maps
# ---------------------------------------------------------------------------

def map_from_arrays(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        kcol, vcol = args
        klen = _lengths(kcol)
        vlen = _lengths(vcol)
        ok = klen == vlen       # mismatched lengths -> NULL map
        return _rebuild(typ, np.where(ok, klen, 0),
                        [kcol.children[0], vcol.children[0]]), \
            _and_all(ok, *valids)

    return impl


def map_keys(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        return _rebuild(typ, _lengths(col), [col.children[0]]), \
            _and_all(*valids)

    return impl


def map_values(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        return _rebuild(typ, _lengths(col), [col.children[1]]), \
            _and_all(*valids)

    return impl


def map_concat(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        rows: List[dict] = [{} for _ in range(n)]
        for col in args:           # later maps win on key collisions
            lengths = _lengths(col)
            row_of = _row_ids(lengths)
            total = int(lengths.sum())
            ks = col.children[0].to_pylist(total)
            vs = col.children[1].to_pylist(total)
            for i, k, v in zip(row_of, ks, vs):
                rows[i][k] = v
        col = column_from_pylist(typ, rows)
        return Column(typ, col.values, None, None, col.children), \
            _and_all(*valids)

    return impl


def _pad_to(col: Column, target_lengths: np.ndarray) -> Column:
    """Re-space an array column's elements to target per-row lengths,
    null-padding the tail (zip/zip_with alignment)."""
    lengths = _lengths(col)
    offsets = _offsets(col)
    total = int(target_lengths.sum())
    row_of = np.repeat(np.arange(lengths.shape[0], dtype=np.int64),
                       target_lengths)
    ends = np.cumsum(target_lengths)
    within = np.arange(total, dtype=np.int64) - \
        np.repeat(ends - target_lengths, target_lengths)
    present = within < lengths[row_of]
    idx = offsets[row_of] + np.minimum(within,
                                       np.maximum(lengths[row_of] - 1, 0))
    kid = col.children[0]
    if kid.values.shape[0] == 0:
        from presto_tpu.batch import empty_column

        out = empty_column(kid.type).pad(total)
        return Column(out.type, out.values, np.zeros(total, bool),
                      out.dictionary, out.children)
    idx = np.clip(idx, 0, kid.values.shape[0] - 1)
    taken = kid.take(idx)
    valid = present if taken.valid is None \
        else present & np.asarray(taken.valid)
    return Column(taken.type, taken.values, valid, taken.dictionary,
                  taken.children)


def zip_fn(typ: T.Type):
    """zip(a1, a2, ...) -> array(row(...)), null-padded to the longest."""

    def impl(args, valids, n, xp) -> Pair:
        maxlen = _lengths(args[0])
        for c in args[1:]:
            maxlen = np.maximum(maxlen, _lengths(c))
        kids = tuple(_pad_to(c, maxlen) for c in args)
        total = int(maxlen.sum())
        row_col = Column(typ.element, np.zeros(total, np.int8), None,
                         None, kids)
        return _rebuild(typ, maxlen, [row_col]), _and_all(*valids)

    return impl


def zip_with(typ: T.Type):
    """zip_with(a, b, (x, y) -> f): elementwise over null-padded pairs."""

    def impl(args, valids, n, xp, lambdas=None) -> Pair:
        a, b = args
        body = lambdas[0]
        maxlen = np.maximum(_lengths(a), _lengths(b))
        ka = _pad_to(a, maxlen)
        kb = _pad_to(b, maxlen)
        total = int(maxlen.sum())
        out_vals, out_valid = body([ka, kb], _row_ids(maxlen), total)
        kid = _kid_from_value(typ.element, out_vals, out_valid)
        return _rebuild(typ, maxlen, [kid]), _and_all(*valids)

    return impl


def map_entries(typ: T.Type):
    """map_entries(m) -> array(row(key, value))."""

    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        lengths = _lengths(col)
        total = int(lengths.sum())
        row_col = Column(typ.element, np.zeros(total, np.int8), None,
                         None, tuple(col.children))
        return _rebuild(typ, lengths, [row_col]), _and_all(*valids)

    return impl


def array_average():
    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        lengths = _lengths(col)
        row_of = _row_ids(lengths)
        kid = col.children[0]
        vals = np.asarray(kid.values, np.float64)
        live = np.ones(vals.shape[0], bool) if kid.valid is None \
            else np.asarray(kid.valid)
        sums = np.zeros(n, np.float64)
        cnts = np.zeros(n, np.int64)
        np.add.at(sums, row_of[live], vals[live])
        np.add.at(cnts, row_of[live], 1)
        ok = cnts > 0
        out = sums / np.maximum(cnts, 1)
        return out, _and_all(ok, *valids)

    return impl


def map_from_entries(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        (col,) = args                   # array(row(k, v))
        row_kid = col.children[0]
        k, v = row_kid.children
        return _rebuild(typ, _lengths(col), [k, v]), _and_all(*valids)

    return impl


def rows_extreme_by(mode: str):
    """x at the min/max y over array(row(x, y)) (min_by/max_by finalize)."""

    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        lengths = _lengths(col)
        row_of = _row_ids(lengths)
        xcol, ycol = col.children[0].children
        yv = np.asarray(ycol.values)
        if ycol.type.is_dictionary and len(ycol.dictionary):
            keyv = ycol.dictionary.sort_ranks()[yv]
        else:
            keyv = yv
        live = np.ones(yv.shape[0], bool) if ycol.valid is None \
            else np.asarray(ycol.valid)
        if yv.shape[0] == 0:
            from presto_tpu.batch import empty_column

            return _kid_result(empty_column(xcol.type).pad(n), n), \
                np.zeros(n, bool)
        order = np.argsort(keyv, kind="stable")
        if mode == "max_by":
            order = order[::-1]
        order = order[live[order]]      # null y never wins
        best = np.zeros(n, np.int64)
        seen = np.zeros(n, bool)
        best[row_of[order[::-1]]] = order[::-1]
        seen[row_of[order]] = True
        taken = xcol.take(best)
        valid = seen
        if taken.valid is not None:
            valid = valid & np.asarray(taken.valid)
        return _kid_result(taken.with_values(taken.values, None), n), \
            _and_all(valid, *valids)

    return impl


def array_percentile(p: float):
    """Exact percentile of collected values (approx_percentile finalize;
    exact beats the reference's qdigest error bound)."""

    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        lengths = _lengths(col)
        offsets = _offsets(col)
        kid = col.children[0]
        kv = np.asarray(kid.values)
        live = np.ones(kv.shape[0], bool) if kid.valid is None \
            else np.asarray(kid.valid)
        out = np.zeros(n, kid.type.np_dtype)
        ok = np.zeros(n, bool)
        for i in range(n):
            lo, hi = int(offsets[i]), int(offsets[i + 1])
            vals = kv[lo:hi][live[lo:hi]]
            if vals.shape[0] == 0:
                continue
            vals = np.sort(vals)
            idx = min(int(np.ceil(p * vals.shape[0])) - 1,
                      vals.shape[0] - 1)
            out[i] = vals[max(idx, 0)]
            ok[i] = True
        return out, _and_all(ok, *valids)

    return impl


def rows_statistic(stat: str):
    """corr / covar_samp / covar_pop / regr_slope / regr_intercept over
    collected array(row(y, x)) pairs (AggregationUtils formulas in the
    reference's DoubleCovarianceAggregation / DoubleRegressionAggregation).
    """

    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        lengths = _lengths(col)
        offsets = _offsets(col)
        ycol, xcol = col.children[0].children
        yv = np.asarray(ycol.values, np.float64)
        xv = np.asarray(xcol.values, np.float64)
        live = np.ones(yv.shape[0], bool)
        if ycol.valid is not None:
            live &= np.asarray(ycol.valid)
        if xcol.valid is not None:
            live &= np.asarray(xcol.valid)
        out = np.zeros(n, np.float64)
        ok = np.zeros(n, bool)
        for i in range(n):
            lo, hi = int(offsets[i]), int(offsets[i + 1])
            m = live[lo:hi]
            y = yv[lo:hi][m]
            x = xv[lo:hi][m]
            cnt = x.shape[0]
            if cnt == 0:
                continue
            mx, my = x.mean(), y.mean()
            cxy = ((x - mx) * (y - my)).sum()
            cxx = ((x - mx) ** 2).sum()
            cyy = ((y - my) ** 2).sum()
            if stat == "covar_pop":
                out[i] = cxy / cnt
                ok[i] = True
            elif stat == "covar_samp":
                if cnt > 1:
                    out[i] = cxy / (cnt - 1)
                    ok[i] = True
            elif stat == "corr":
                if cxx > 0 and cyy > 0:
                    out[i] = cxy / np.sqrt(cxx * cyy)
                    ok[i] = True
            elif stat == "regr_slope":
                if cxx > 0:
                    out[i] = cxy / cxx
                    ok[i] = True
            elif stat == "regr_intercept":
                if cxx > 0:
                    out[i] = my - (cxy / cxx) * mx
                    ok[i] = True
        return out, _and_all(ok, *valids)

    return impl


# ---------------------------------------------------------------------------
# rows
# ---------------------------------------------------------------------------

def row_field(field_index: int):
    def impl(args, valids, n, xp) -> Pair:
        (col,) = args
        kid = col.children[field_index]
        kid_valid = None if kid.valid is None else np.asarray(kid.valid)
        valid = _and_all(kid_valid, *valids)
        return _kid_result(kid.with_values(kid.values, None), n), valid

    return impl


def array_constructor(typ: T.Type, k: int):
    """ARRAY[e1, ..., ek]: k element expressions -> length-k rows.

    NULL elements stay as null entries; the array itself is never NULL.
    """

    def impl(args, valids, n, xp) -> Pair:
        if k == 0:
            from presto_tpu.batch import empty_column

            return _rebuild(typ, np.zeros(n, np.int64),
                            [empty_column(typ.element)]), None
        kids = []
        for v, vv in zip(args, valids):
            if isinstance(v, Column):
                kid = Column(typ.element, v.values, vv, v.dictionary,
                             v.children)
            elif isinstance(v, str):
                kid = Column(typ.element, np.zeros(n, np.int32), vv,
                             Dictionary([v]))
            else:
                vals = np.broadcast_to(
                    np.asarray(v), (n,)).astype(typ.element.np_dtype)
                kid = Column(typ.element, vals, vv)
            kids.append(kid)
        flat = _concat_columns(kids, [n] * k) if k > 1 else kids[0]
        # concat layout is [j*n + i]; rows need [i*k + j]
        g = (np.arange(k)[None, :] * n
             + np.arange(n)[:, None]).ravel().astype(np.int64)
        flat = _take_kid(flat, g)
        return _rebuild(typ, np.full(n, k, np.int64), [flat]), None

    return impl


def row_constructor(typ: T.Type):
    def impl(args, valids, n, xp) -> Pair:
        kids = []
        for ft, v, vv in zip(typ.field_types, args, valids):
            if isinstance(v, Column):
                kid = Column(ft, v.values, vv, v.dictionary, v.children)
            elif isinstance(v, str):
                d = Dictionary([v])
                kid = Column(ft, np.zeros(n, np.int32), vv, d)
            else:
                vals = np.broadcast_to(
                    np.asarray(v, ft.np_dtype), (n,)).copy()
                kid = Column(ft, vals, vv)
            kids.append(kid)
        return Column(typ, np.zeros(n, np.int8), None, None, tuple(kids)), \
            None

    return impl


# ---------------------------------------------------------------------------
# lambdas (ArrayTransformFunction / ArrayFilterFunction / ReduceFunction /
# MapFilter / TransformValues analogues).  ``body`` is a runtime evaluator
# built by compile.py: body(pairs, n_elems) -> (values, valid) over the
# FLATTENED element domain, with outer captures repeated per element.
# ---------------------------------------------------------------------------

def transform(typ: T.Type):
    def impl(args, valids, n, xp, lambdas=None) -> Pair:
        col = args[0]
        body = lambdas[0]
        lengths = _lengths(col)
        kid = col.children[0]
        total = int(lengths.sum())
        out_vals, out_valid = body([kid], _row_ids(lengths), total)
        new_kid = _kid_from_value(typ.element, out_vals, out_valid)
        return _rebuild(typ, lengths, [new_kid]), _and_all(*valids)

    return impl


def filter_fn(typ: T.Type):
    def impl(args, valids, n, xp, lambdas=None) -> Pair:
        col = args[0]
        body = lambdas[0]
        lengths = _lengths(col)
        kid = col.children[0]
        total = int(lengths.sum())
        row_of = _row_ids(lengths)
        keep_vals, keep_valid = body([kid], row_of, total)
        keep = np.asarray(keep_vals, bool)
        if keep_valid is not None:               # NULL predicate drops
            keep = keep & np.asarray(keep_valid)
        new_lengths = np.zeros(n, np.int64)
        np.add.at(new_lengths, row_of[keep], 1)
        kid2 = _take_kid(kid, np.nonzero(keep)[0])
        return _rebuild(typ, new_lengths, [kid2]), _and_all(*valids)

    return impl


def map_filter(typ: T.Type):
    def impl(args, valids, n, xp, lambdas=None) -> Pair:
        col = args[0]
        body = lambdas[0]
        lengths = _lengths(col)
        kcol, vcol = col.children
        total = int(lengths.sum())
        row_of = _row_ids(lengths)
        keep_vals, keep_valid = body([kcol, vcol], row_of, total)
        keep = np.asarray(keep_vals, bool)
        if keep_valid is not None:
            keep = keep & np.asarray(keep_valid)
        new_lengths = np.zeros(n, np.int64)
        np.add.at(new_lengths, row_of[keep], 1)
        idx = np.nonzero(keep)[0]
        return _rebuild(typ, new_lengths,
                        [_take_kid(kcol, idx), _take_kid(vcol, idx)]), \
            _and_all(*valids)

    return impl


def transform_values(typ: T.Type):
    def impl(args, valids, n, xp, lambdas=None) -> Pair:
        col = args[0]
        body = lambdas[0]
        lengths = _lengths(col)
        kcol, vcol = col.children
        total = int(lengths.sum())
        out_vals, out_valid = body([kcol, vcol], _row_ids(lengths), total)
        new_v = _kid_from_value(typ.value, out_vals, out_valid)
        return _rebuild(typ, lengths, [kcol, new_v]), _and_all(*valids)

    return impl


def transform_keys(typ: T.Type):
    def impl(args, valids, n, xp, lambdas=None) -> Pair:
        col = args[0]
        body = lambdas[0]
        lengths = _lengths(col)
        kcol, vcol = col.children
        total = int(lengths.sum())
        out_vals, out_valid = body([kcol, vcol], _row_ids(lengths), total)
        new_k = _kid_from_value(typ.key, out_vals, out_valid)
        return _rebuild(typ, lengths, [new_k, vcol]), _and_all(*valids)

    return impl


def reduce_fn(result_type: T.Type):
    """reduce(array, init, (state, x) -> ..., state -> ...).

    The combine lambda folds sequentially *within* a row but all rows fold
    in lockstep: iteration k combines every row's state with its k-th
    element at once — max(lengths) vectorized passes instead of
    total-elements scalar steps.
    """

    def impl(args, valids, n, xp, lambdas=None) -> Pair:
        col, init = args[0], args[1]
        combine, finish = lambdas
        lengths = _lengths(col)
        offsets = _offsets(col)
        kid = col.children[0]
        if isinstance(init, Column):
            raise NotImplementedError("nested reduce state")
        state = np.broadcast_to(np.asarray(init), (n,)).copy()
        state_valid = None if valids[1] is None else valids[1].copy()
        kmax = int(lengths.max()) if n else 0
        for k in range(kmax):
            rows = np.nonzero(lengths > k)[0]
            elem_idx = offsets[rows] + k
            elem = kid.take(elem_idx)
            state_col = Column(T.DOUBLE if state.dtype.kind == "f"
                               else T.BIGINT, state[rows],
                               None if state_valid is None
                               else state_valid[rows])
            out_vals, out_valid = combine([state_col, elem],
                                          rows, rows.shape[0])
            state[rows] = np.asarray(out_vals)
            if out_valid is not None:
                if state_valid is None:
                    state_valid = np.ones(n, bool)
                state_valid[rows] = np.asarray(out_valid)
        final_col = Column(T.DOUBLE if state.dtype.kind == "f"
                           else T.BIGINT, state,
                           None if state_valid is None else state_valid)
        out_vals, out_valid = finish([final_col],
                                     np.arange(n, dtype=np.int64), n)
        return out_vals, _and_all(out_valid, valids[0])

    return impl


def any_all_none_match(mode: str):
    def impl(args, valids, n, xp, lambdas=None) -> Pair:
        col = args[0]
        body = lambdas[0]
        lengths = _lengths(col)
        kid = col.children[0]
        total = int(lengths.sum())
        row_of = _row_ids(lengths)
        mvals, mvalid = body([kid], row_of, total)
        m = np.asarray(mvals, bool)
        if mvalid is not None:
            m = m & np.asarray(mvalid)
        hit = np.zeros(n, bool)
        np.logical_or.at(hit, row_of, m)
        if mode == "any":
            out = hit
        elif mode == "all":
            miss = np.zeros(n, bool)
            np.logical_or.at(miss, row_of, ~m)
            out = ~miss
        else:
            out = ~hit
        return out, _and_all(*valids)

    return impl


def _kid_from_value(typ: T.Type, values, valid) -> Column:
    if isinstance(values, Column):
        return Column(typ, values.values, valid, values.dictionary,
                      values.children)
    if typ.is_dictionary:
        # lambda over strings produced raw codes + dictionary is carried on
        # the Column; a bare code array cannot appear here
        raise NotImplementedError("string lambda results need a dictionary")
    return Column(typ, np.asarray(values), valid)


def rows_learn(mode: str):
    """learn_classifier / learn_regressor finalize over collected
    array(row(label, features_json)) pairs: train per group, emit the
    model as JSON varchar (presto-ml LearnClassifierAggregation role —
    see expr/ml.py for the estimators)."""
    out_dict = Dictionary()

    def impl(args, valids, n, xp) -> Pair:
        from presto_tpu.expr import ml

        (col,) = args
        offsets = _offsets(col)
        lcol, fcol = col.children[0].children
        labels = lcol.to_pylist(int(offsets[-1]))
        feats = fcol.to_pylist(int(offsets[-1]))
        codes = np.zeros(n, np.int32)
        ok = np.zeros(n, bool)
        for i in range(n):
            lo, hi = int(offsets[i]), int(offsets[i + 1])
            pairs = [(l, f) for l, f in zip(labels[lo:hi], feats[lo:hi])
                     if l is not None and f is not None]
            if not pairs:
                continue
            ls = [p[0] for p in pairs]
            fs = [p[1] for p in pairs]
            model = (ml.train_classifier(ls, fs)
                     if mode == "learn_classifier"
                     else ml.train_regressor(ls, fs))
            codes[i] = out_dict.intern(model)
            ok[i] = True
        return Column(T.VARCHAR, codes, None, out_dict), ok

    return impl
