"""Typed RowExpression builders.

The reference builds RowExpressions in SqlToRowExpressionTranslator
(presto-main/.../sql/relational/SqlToRowExpressionTranslator.java:122),
resolving overloads against the FunctionRegistry and inserting coercions.
These helpers do the same for planner/test code.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.expr import functions as F
from presto_tpu.expr.ir import Call, Constant, InputRef, RowExpression, SpecialForm


def ref(index: int, typ: T.Type) -> InputRef:
    return InputRef(index, typ)


def const(value: Any, typ: T.Type) -> Constant:
    """Literal from a *Python-domain* value (converted to storage domain)."""
    if value is None:
        return Constant(None, typ)
    if typ.is_dictionary:
        return Constant(str(value), typ)
    return Constant(typ.from_python(value), typ)


def null(typ: T.Type) -> Constant:
    return Constant(None, typ)


def call(name: str, *args: RowExpression) -> Call:
    fn = F.resolve_scalar(name, [a.type for a in args])
    return Call(name, tuple(args), fn.result_type, fn)


def cast(expr: RowExpression, to: T.Type) -> RowExpression:
    if expr.type == to:
        return expr
    fn = F.resolve_cast(expr.type, to)
    return Call("cast", (expr,), to, fn)


def round_digits(expr: RowExpression, digits: int) -> Call:
    fn = F.resolve_round(expr.type, digits)
    return Call("round", (expr,), fn.result_type, fn)


def and_(*exprs: RowExpression) -> RowExpression:
    exprs = tuple(e for e in exprs if e is not None)
    if not exprs:
        return const(True, T.BOOLEAN)
    out = exprs[0]
    for e in exprs[1:]:
        out = SpecialForm("AND", (out, e), T.BOOLEAN)
    return out


def or_(*exprs: RowExpression) -> RowExpression:
    out = exprs[0]
    for e in exprs[1:]:
        out = SpecialForm("OR", (out, e), T.BOOLEAN)
    return out


def not_(expr: RowExpression) -> Call:
    return call("not", expr)


def if_(cond: RowExpression, then: RowExpression,
        other: Optional[RowExpression] = None) -> SpecialForm:
    if other is None:
        other = null(then.type)
    t = T.common_super_type(then.type, other.type) or then.type
    return SpecialForm("IF", (cond, cast(then, t), cast(other, t)), t)


def coalesce(*exprs: RowExpression) -> SpecialForm:
    t = exprs[0].type
    for e in exprs[1:]:
        t = T.common_super_type(t, e.type) or t
    return SpecialForm("COALESCE", tuple(cast(e, t) for e in exprs), t)


def in_(value: RowExpression, items: Sequence[RowExpression]) -> SpecialForm:
    if not T.is_string(value.type):
        t = value.type
        for i in items:
            t = T.common_super_type(t, i.type) or t
        value = cast(value, t)
        items = [cast(i, t) for i in items]
    return SpecialForm("IN", (value, *items), T.BOOLEAN)


def between(value: RowExpression, lo: RowExpression,
            hi: RowExpression) -> RowExpression:
    return and_(call("ge", value, lo), call("le", value, hi))


def comparison(op: str, left: RowExpression, right: RowExpression) -> Call:
    name = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt", "<=": "le",
            ">": "gt", ">=": "ge"}[op]
    # an untyped NULL side takes the other side's type (the analyzer's
    # unknown-coercion rule); the comparison then yields NULL rows
    if left.type == T.UNKNOWN and right.type != T.UNKNOWN:
        left = cast(left, right.type)
    elif right.type == T.UNKNOWN and left.type != T.UNKNOWN:
        right = cast(right, left.type)
    return call(name, left, right)


def case_when(pairs, default: Optional[RowExpression],
              result_type: Optional[T.Type] = None) -> SpecialForm:
    """pairs: [(cond, value), ...]; searched CASE."""
    t = result_type
    if t is None:
        t = pairs[0][1].type
        for _, v in pairs[1:]:
            t = T.common_super_type(t, v.type) or t
        if default is not None:
            t = T.common_super_type(t, default.type) or t
    default = cast(default, t) if default is not None else null(t)
    args = [default]
    for cond, v in pairs:
        args.append(cond)
        args.append(cast(v, t))
    return SpecialForm("SWITCH", tuple(args), t)
