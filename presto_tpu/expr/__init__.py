"""Expression engine: RowExpression IR -> dual-backend (numpy oracle / XLA)
evaluation.  Replaces the reference's runtime-bytecode tier
(presto-main/.../sql/gen/ExpressionCompiler.java:55, SURVEY §2.7)."""

from presto_tpu.expr.ir import (  # noqa: F401
    Call, Constant, InputRef, RowExpression, SpecialForm,
)
