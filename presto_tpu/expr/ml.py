"""ML functions: learn_classifier / classify, learn_regressor / regress.

The presto-ml role (3,449 LoC: learn_classifier/learn_regressor
aggregates train a libsvm model over collected (label, features) pairs;
classify/regress scalars apply it; features(...) builds a FeatureVector).
Here models are trained in numpy — multinomial logistic regression for
classification, ridge-regularized least squares for regression — and
serialized as JSON varchar so they flow through the engine as ordinary
values (the reference's Model/Classifier SQL type role).

Reference: presto-ml/src/main/java/io/prestosql/plugin/ml/
LearnClassifierAggregation.java, ClassifyFunctions.java,
MLFeaturesFunctions.java.
"""

from __future__ import annotations

import json
import math
from typing import List, Optional, Sequence, Tuple

import numpy as np


def features(*xs: float) -> str:
    """Feature vector as a JSON array (FeatureVector analogue)."""
    return json.dumps([float(x) for x in xs])


def _feature_matrix(fjsons: Sequence[str]) -> np.ndarray:
    rows = [json.loads(f) for f in fjsons]
    width = max((len(r) for r in rows), default=0)
    X = np.zeros((len(rows), width))
    for i, r in enumerate(rows):
        X[i, :len(r)] = r
    return X


def train_classifier(labels: Sequence, fjsons: Sequence[str],
                     iters: int = 300, lr: float = 0.5) -> str:
    """Multinomial logistic regression by full-batch gradient descent
    (the libsvm-classifier role; softmax instead of SVM)."""
    X = _feature_matrix(fjsons)
    classes = sorted({str(l) for l in labels})
    idx = {c: i for i, c in enumerate(classes)}
    y = np.asarray([idx[str(l)] for l in labels])
    n, d = X.shape
    k = len(classes)
    # standardize for conditioning; bake the transform into the model
    mu = X.mean(axis=0) if n else np.zeros(d)
    sd = X.std(axis=0) if n else np.ones(d)
    sd = np.where(sd > 0, sd, 1.0)
    Xs = (X - mu) / sd
    W = np.zeros((d, k))
    b = np.zeros(k)
    onehot = np.eye(k)[y] if n else np.zeros((0, k))
    for _ in range(iters):
        logits = Xs @ W + b
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(axis=1, keepdims=True)
        g = (p - onehot) / max(n, 1)
        W -= lr * (Xs.T @ g + 1e-4 * W)
        b -= lr * g.sum(axis=0)
    return json.dumps({
        "kind": "classifier", "classes": classes, "mu": mu.tolist(),
        "sd": sd.tolist(), "w": W.tolist(), "b": b.tolist()})


def train_regressor(ys: Sequence[float], fjsons: Sequence[str]) -> str:
    """Ridge-regularized least squares (closed form)."""
    X = _feature_matrix(fjsons)
    y = np.asarray([float(v) for v in ys])
    n, d = X.shape
    Xb = np.hstack([X, np.ones((n, 1))])
    A = Xb.T @ Xb + 1e-6 * np.eye(d + 1)
    w = np.linalg.solve(A, Xb.T @ y) if n else np.zeros(d + 1)
    return json.dumps({"kind": "regressor", "w": w[:-1].tolist(),
                       "b": float(w[-1])})


def classify(fjson: str, model_json: str) -> str:
    m = json.loads(model_json)
    if m.get("kind") != "classifier":
        raise ValueError("classify() needs a learn_classifier model")
    x = np.asarray(json.loads(fjson), dtype=float)
    d = len(m["mu"])
    xp = np.zeros(d)
    xp[:min(len(x), d)] = x[:d]
    xs = (xp - np.asarray(m["mu"])) / np.asarray(m["sd"])
    logits = xs @ np.asarray(m["w"]) + np.asarray(m["b"])
    return m["classes"][int(np.argmax(logits))]


def regress(fjson: str, model_json: str) -> float:
    m = json.loads(model_json)
    if m.get("kind") != "regressor":
        raise ValueError("regress() needs a learn_regressor model")
    x = np.asarray(json.loads(fjson), dtype=float)
    w = np.asarray(m["w"])
    d = len(w)
    xp = np.zeros(d)
    xp[:min(len(x), d)] = x[:d]
    return float(xp @ w + m["b"])
