"""Geospatial functions: WKT geometries in pure numpy/python.

The presto-geospatial role (11,123 + 3,071 LoC: ST_* scalar functions on
an Esri geometry library, Bing tile functions, KDB-tree spatial
partitioning).  Here geometries are **WKT varchar values** — every ST_*
function parses WKT, computes in numpy, and emits WKT or a scalar; the
host-side string-function path evaluates per dictionary entry or row,
and joins on ST_Contains/ST_Distance predicates run through the
nested-loop join with the predicate as a residual filter (the
SpatialJoinOperator's correctness contract; its R-tree is a pure
optimization).

Supported: POINT, MULTIPOINT, LINESTRING, MULTILINESTRING, POLYGON
(with holes), MULTIPOLYGON.  Containment of area geometries uses the
all-vertices-inside + no-edge-crossing test.

Reference: presto-geospatial/src/main/java/io/prestosql/plugin/geospatial/
GeoFunctions.java (ST_* signatures), BingTileFunctions.java.
"""

from __future__ import annotations

import math
import re
from typing import List, Optional, Sequence, Tuple

Ring = List[Tuple[float, float]]


class Geometry:
    """kind: point|multipoint|linestring|multilinestring|polygon|
    multipolygon.  ``polys`` is [(shell, [holes...])]; points/lines use
    ``paths`` (list of coordinate lists)."""

    def __init__(self, kind: str, paths: List[Ring],
                 polys: List[Tuple[Ring, List[Ring]]]):
        self.kind = kind
        self.paths = paths
        self.polys = polys

    # -- derived --------------------------------------------------------
    def vertices(self) -> Ring:
        out: Ring = []
        for p in self.paths:
            out.extend(p)
        for shell, holes in self.polys:
            out.extend(shell)
            for h in holes:
                out.extend(h)
        return out

    def edges(self) -> List[Tuple[Tuple[float, float],
                                  Tuple[float, float]]]:
        out = []
        for p in self.paths:
            if self.kind in ("point", "multipoint"):
                continue
            out.extend(zip(p, p[1:]))
        for shell, holes in self.polys:
            for ring in [shell] + holes:
                out.extend(zip(ring, ring[1:] + ring[:1]))
        return out

    def bbox(self):
        vs = self.vertices()
        xs = [x for x, _ in vs]
        ys = [y for _, y in vs]
        return min(xs), min(ys), max(xs), max(ys)

    def is_area(self) -> bool:
        return bool(self.polys)


# --- WKT parse / format -----------------------------------------------------

_NUM = r"[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?"


def _parse_coords(body: str) -> Ring:
    pts = []
    for pair in body.split(","):
        nums = re.findall(_NUM, pair)
        if len(nums) < 2:
            raise ValueError(f"bad WKT coordinates {pair!r}")
        pts.append((float(nums[0]), float(nums[1])))
    return pts


def _split_groups(body: str) -> List[str]:
    """Split 'a, b), (c' style top-level parenthesized groups."""
    groups, depth, cur = [], 0, []
    for ch in body:
        if ch == "(":
            depth += 1
            if depth == 1:
                cur = []
                continue
        elif ch == ")":
            depth -= 1
            if depth == 0:
                groups.append("".join(cur))
                continue
        if depth >= 1:
            cur.append(ch)
    return groups


def parse_wkt(wkt: str) -> Geometry:
    s = wkt.strip()
    m = re.match(r"(?i)\s*([a-z]+)\s*(empty|\(.*\))\s*$", s, re.S)
    if not m:
        raise ValueError(f"bad WKT: {wkt!r}")
    kind = m.group(1).lower()
    body = m.group(2)
    if body.lower() == "empty":
        return Geometry(kind, [], [])
    inner = body.strip()[1:-1]
    if kind == "point":
        return Geometry("point", [_parse_coords(inner)], [])
    if kind == "multipoint":
        inner2 = inner.replace("(", "").replace(")", "")
        return Geometry("multipoint", [_parse_coords(inner2)], [])
    if kind == "linestring":
        return Geometry("linestring", [_parse_coords(inner)], [])
    if kind == "multilinestring":
        return Geometry("multilinestring",
                        [_parse_coords(g) for g in _split_groups(inner)],
                        [])
    if kind == "polygon":
        rings = [_parse_coords(g) for g in _split_groups(inner)]
        return Geometry("polygon", [],
                        [(rings[0], rings[1:])] if rings else [])
    if kind == "multipolygon":
        polys = []
        depth, start = 0, None
        groups: List[str] = []
        for i, ch in enumerate(body):
            if ch == "(":
                depth += 1
                if depth == 2:
                    start = i
            elif ch == ")":
                if depth == 2 and start is not None:
                    groups.append(body[start:i + 1])
                depth -= 1
        for g in groups:
            rings = [_parse_coords(r) for r in _split_groups(g)]
            if rings:
                polys.append((rings[0], rings[1:]))
        return Geometry("multipolygon", [], polys)
    raise ValueError(f"unsupported WKT geometry {kind!r}")


def _fmt_pt(p: Tuple[float, float]) -> str:
    return f"{_n(p[0])} {_n(p[1])}"


def _n(x: float) -> str:
    return repr(int(x)) if float(x).is_integer() else repr(float(x))


def format_wkt(g: Geometry) -> str:
    if not g.vertices():
        return f"{g.kind.upper()} EMPTY"
    if g.kind == "point":
        return f"POINT ({_fmt_pt(g.paths[0][0])})"
    if g.kind == "multipoint":
        pts = ", ".join(_fmt_pt(p) for p in g.paths[0])
        return f"MULTIPOINT ({pts})"
    if g.kind == "linestring":
        return ("LINESTRING ("
                + ", ".join(_fmt_pt(p) for p in g.paths[0]) + ")")
    if g.kind == "multilinestring":
        parts = ", ".join(
            "(" + ", ".join(_fmt_pt(p) for p in path) + ")"
            for path in g.paths)
        return f"MULTILINESTRING ({parts})"
    if g.kind in ("polygon", "multipolygon"):
        def poly(shell_holes):
            shell, holes = shell_holes
            rings = [shell] + holes
            return ("(" + ", ".join(
                "(" + ", ".join(_fmt_pt(p) for p in r) + ")"
                for r in rings) + ")")
        if g.kind == "polygon":
            return "POLYGON " + poly(g.polys[0])
        return ("MULTIPOLYGON ("
                + ", ".join(poly(ph) for ph in g.polys) + ")")
    raise ValueError(g.kind)


# --- geometric primitives ---------------------------------------------------

def _ring_area(ring: Ring) -> float:
    s = 0.0
    for (x1, y1), (x2, y2) in zip(ring, ring[1:] + ring[:1]):
        s += x1 * y2 - x2 * y1
    return s / 2.0


def _point_in_ring(pt: Tuple[float, float], ring: Ring) -> bool:
    """Ray casting; boundary counts as inside."""
    x, y = pt
    inside = False
    for (x1, y1), (x2, y2) in zip(ring, ring[1:] + ring[:1]):
        if _on_segment(pt, (x1, y1), (x2, y2)):
            return True
        if (y1 > y) != (y2 > y):
            xint = x1 + (y - y1) * (x2 - x1) / (y2 - y1)
            if x < xint:
                inside = not inside
    return inside


def _on_segment(p, a, b, eps: float = 1e-12) -> bool:
    (px, py), (ax, ay), (bx, by) = p, a, b
    cross = (bx - ax) * (py - ay) - (by - ay) * (px - ax)
    if abs(cross) > eps * max(1.0, abs(bx - ax), abs(by - ay)):
        return False
    return (min(ax, bx) - eps <= px <= max(ax, bx) + eps
            and min(ay, by) - eps <= py <= max(ay, by) + eps)


def _point_in_poly(pt, shell_holes) -> bool:
    shell, holes = shell_holes
    if not _point_in_ring(pt, shell):
        return False
    for h in holes:
        if _point_in_ring(pt, h) and not any(
                _on_segment(pt, a, b)
                for a, b in zip(h, h[1:] + h[:1])):
            return False
    return True


def _point_in_geom_area(pt, g: Geometry) -> bool:
    return any(_point_in_poly(pt, ph) for ph in g.polys)


def _seg_intersect(a, b, c, d) -> bool:
    def ccw(p, q, r):
        return ((r[1] - p[1]) * (q[0] - p[0])
                - (q[1] - p[1]) * (r[0] - p[0]))

    d1, d2 = ccw(c, d, a), ccw(c, d, b)
    d3, d4 = ccw(a, b, c), ccw(a, b, d)
    if ((d1 > 0) != (d2 > 0)) and ((d3 > 0) != (d4 > 0)):
        return True
    for p, (u, v) in ((a, (c, d)), (b, (c, d)), (c, (a, b)), (d, (a, b))):
        if _on_segment(p, u, v):
            return True
    return False


def _pt_seg_dist(p, a, b) -> float:
    (px, py), (ax, ay), (bx, by) = p, a, b
    dx, dy = bx - ax, by - ay
    if dx == dy == 0:
        return math.hypot(px - ax, py - ay)
    t = max(0.0, min(1.0, ((px - ax) * dx + (py - ay) * dy)
                     / (dx * dx + dy * dy)))
    return math.hypot(px - (ax + t * dx), py - (ay + t * dy))


# --- ST_* implementations ---------------------------------------------------

def st_point(x: float, y: float) -> str:
    return f"POINT ({_n(float(x))} {_n(float(y))})"


def st_x(wkt: str) -> Optional[float]:
    g = parse_wkt(wkt)
    if g.kind != "point":
        raise ValueError("ST_X requires a POINT")
    return g.paths[0][0][0] if g.vertices() else None


def st_y(wkt: str) -> Optional[float]:
    g = parse_wkt(wkt)
    if g.kind != "point":
        raise ValueError("ST_Y requires a POINT")
    return g.paths[0][0][1] if g.vertices() else None


def st_area(wkt: str) -> float:
    g = parse_wkt(wkt)
    total = 0.0
    for shell, holes in g.polys:
        total += abs(_ring_area(shell))
        for h in holes:
            total -= abs(_ring_area(h))
    return total


def st_length(wkt: str) -> float:
    g = parse_wkt(wkt)
    total = 0.0
    for path in g.paths:
        if g.kind in ("linestring", "multilinestring"):
            for a, b in zip(path, path[1:]):
                total += math.hypot(b[0] - a[0], b[1] - a[1])
    return total


def st_perimeter(wkt: str) -> float:
    g = parse_wkt(wkt)
    total = 0.0
    for shell, holes in g.polys:
        for ring in [shell] + holes:
            for a, b in zip(ring, ring[1:] + ring[:1]):
                total += math.hypot(b[0] - a[0], b[1] - a[1])
    return total


def st_centroid(wkt: str) -> Optional[str]:
    g = parse_wkt(wkt)
    if not g.vertices():
        return None
    if g.is_area():
        # area-weighted centroid over shells (holes subtract)
        ax = ay = aa = 0.0
        for shell, holes in g.polys:
            for ring, sign in [(shell, 1.0)] + [(h, -1.0) for h in holes]:
                a2 = _ring_area(ring)
                if a2 == 0:
                    continue
                cx = cy = 0.0
                for (x1, y1), (x2, y2) in zip(ring,
                                              ring[1:] + ring[:1]):
                    cross = x1 * y2 - x2 * y1
                    cx += (x1 + x2) * cross
                    cy += (y1 + y2) * cross
                cx /= (6 * a2)
                cy /= (6 * a2)
                w = sign * abs(a2)
                ax += cx * w
                ay += cy * w
                aa += w
        if aa == 0:
            vs = g.vertices()
            return st_point(sum(x for x, _ in vs) / len(vs),
                            sum(y for _, y in vs) / len(vs))
        return st_point(ax / aa, ay / aa)
    vs = g.vertices()
    return st_point(sum(x for x, _ in vs) / len(vs),
                    sum(y for _, y in vs) / len(vs))


def st_envelope(wkt: str) -> Optional[str]:
    g = parse_wkt(wkt)
    if not g.vertices():
        return None
    x0, y0, x1, y1 = g.bbox()
    return (f"POLYGON (({_n(x0)} {_n(y0)}, {_n(x1)} {_n(y0)}, "
            f"{_n(x1)} {_n(y1)}, {_n(x0)} {_n(y1)}, {_n(x0)} {_n(y0)}))")


def _bbox_disjoint(a: Geometry, b: Geometry) -> bool:
    ax0, ay0, ax1, ay1 = a.bbox()
    bx0, by0, bx1, by1 = b.bbox()
    return ax1 < bx0 or bx1 < ax0 or ay1 < by0 or by1 < ay0


def st_contains(wkt_a: str, wkt_b: str) -> bool:
    """A contains B: every vertex of B inside A and no edge of B crosses
    out of A (exact for points; the standard approximation for
    area/line operands)."""
    return contains_geoms(parse_wkt(wkt_a), parse_wkt(wkt_b))


def contains_geoms(a: Geometry, b: Geometry) -> bool:
    """st_contains over pre-parsed geometries (the spatial-join hot
    path: candidates are checked without re-parsing WKT per pair)."""
    if not a.vertices() or not b.vertices():
        return False  # EMPTY geometries contain/are contained by nothing
    if not a.is_area():
        return False
    if _bbox_disjoint(a, b):
        return False
    for pt in b.vertices():
        if not _point_in_geom_area(pt, a):
            return False
    # no B edge may cross an A ring boundary
    for e1 in b.edges():
        for e2 in a.edges():
            if _proper_cross(e1[0], e1[1], e2[0], e2[1]):
                return False
    return True


def _proper_cross(a, b, c, d) -> bool:
    def ccw(p, q, r):
        return ((r[1] - p[1]) * (q[0] - p[0])
                - (q[1] - p[1]) * (r[0] - p[0]))

    d1, d2 = ccw(c, d, a), ccw(c, d, b)
    d3, d4 = ccw(a, b, c), ccw(a, b, d)
    return ((d1 > 0) != (d2 > 0)) and ((d3 > 0) != (d4 > 0))


def st_within(wkt_a: str, wkt_b: str) -> bool:
    return st_contains(wkt_b, wkt_a)


def st_intersects(wkt_a: str, wkt_b: str) -> bool:
    return intersects_geoms(parse_wkt(wkt_a), parse_wkt(wkt_b))


def intersects_geoms(a: Geometry, b: Geometry) -> bool:
    if not a.vertices() or not b.vertices():
        return False  # EMPTY intersects nothing
    if _bbox_disjoint(a, b):
        return False
    # any vertex containment either way
    if a.is_area() and any(_point_in_geom_area(p, a)
                           for p in b.vertices()):
        return True
    if b.is_area() and any(_point_in_geom_area(p, b)
                           for p in a.vertices()):
        return True
    # edge crossings
    for e1 in a.edges():
        for e2 in b.edges():
            if _seg_intersect(e1[0], e1[1], e2[0], e2[1]):
                return True
    # point-point coincidence
    if a.kind in ("point", "multipoint") and \
            b.kind in ("point", "multipoint"):
        return bool(set(a.vertices()) & set(b.vertices()))
    return False


def st_distance(wkt_a: str, wkt_b: str) -> Optional[float]:
    return distance_geoms(parse_wkt(wkt_a), parse_wkt(wkt_b))


def distance_geoms(a: Geometry, b: Geometry) -> Optional[float]:
    if not a.vertices() or not b.vertices():
        return None  # NULL for EMPTY operands (reference behavior)
    if intersects_geoms(a, b):
        return 0.0
    best = math.inf
    a_edges = a.edges()
    b_edges = b.edges()
    for p in a.vertices():
        for e in b_edges:
            best = min(best, _pt_seg_dist(p, e[0], e[1]))
        if not b_edges:
            for q in b.vertices():
                best = min(best, math.hypot(p[0] - q[0], p[1] - q[1]))
    for p in b.vertices():
        for e in a_edges:
            best = min(best, _pt_seg_dist(p, e[0], e[1]))
        if not a_edges:
            for q in a.vertices():
                best = min(best, math.hypot(p[0] - q[0], p[1] - q[1]))
    return best


def st_is_valid(wkt: str) -> bool:
    try:
        g = parse_wkt(wkt)
    except ValueError:
        return False
    for shell, _holes in g.polys:
        if len(shell) < 3:
            return False
    return True


def st_geometry_from_text(wkt: str) -> str:
    return format_wkt(parse_wkt(wkt))  # validates + normalizes


def st_astext(wkt: str) -> str:
    return wkt


def st_geometry_type(wkt: str) -> str:
    return "ST_" + {
        "point": "Point", "multipoint": "MultiPoint",
        "linestring": "LineString",
        "multilinestring": "MultiLineString",
        "polygon": "Polygon", "multipolygon": "MultiPolygon",
    }[parse_wkt(wkt).kind]


def st_num_points(wkt: str) -> int:
    return len(parse_wkt(wkt).vertices())


def st_buffer(wkt: str, distance: float, segments: int = 64) -> str:
    """Point buffer as a regular polygon approximation (the common case
    in the reference's tests; other inputs raise)."""
    g = parse_wkt(wkt)
    if g.kind != "point":
        raise ValueError("ST_Buffer supports POINT inputs")
    cx, cy = g.paths[0][0]
    d = float(distance)
    pts = [(cx + d * math.cos(2 * math.pi * i / segments),
            cy + d * math.sin(2 * math.pi * i / segments))
           for i in range(segments)]
    ring = ", ".join(f"{_n(round(x, 12))} {_n(round(y, 12))}"
                     for x, y in pts + [pts[0]])
    return f"POLYGON (({ring}))"


# --- Bing tiles (BingTileFunctions.java) ------------------------------------

_MAX_LAT, _MIN_LAT = 85.05112878, -85.05112878


def bing_tile_at(lat: float, lon: float, zoom: int) -> str:
    """Quadkey of the tile containing (lat, lon) at ``zoom``."""
    zoom = int(zoom)
    if not (1 <= zoom <= 23):
        raise ValueError("zoom must be in [1, 23]")
    lat = min(max(float(lat), _MIN_LAT), _MAX_LAT)
    x = (float(lon) + 180.0) / 360.0
    sin_lat = math.sin(math.radians(lat))
    y = 0.5 - math.log((1 + sin_lat) / (1 - sin_lat)) / (4 * math.pi)
    size = 1 << zoom
    tx = min(size - 1, max(0, int(x * size)))
    ty = min(size - 1, max(0, int(y * size)))
    qk = []
    for i in range(zoom, 0, -1):
        digit = 0
        mask = 1 << (i - 1)
        if tx & mask:
            digit += 1
        if ty & mask:
            digit += 2
        qk.append(str(digit))
    return "".join(qk)


def _quadkey_to_xyz(qk: str) -> Tuple[int, int, int]:
    tx = ty = 0
    zoom = len(qk)
    for i, ch in enumerate(qk):
        mask = 1 << (zoom - i - 1)
        d = int(ch)
        if d & 1:
            tx |= mask
        if d & 2:
            ty |= mask
    return tx, ty, zoom


def bing_tile_zoom_level(qk: str) -> int:
    return len(qk)


def bing_tile_coordinates_x(qk: str) -> int:
    return _quadkey_to_xyz(qk)[0]


def bing_tile_coordinates_y(qk: str) -> int:
    return _quadkey_to_xyz(qk)[1]


def bing_tile_polygon(qk: str) -> str:
    tx, ty, zoom = _quadkey_to_xyz(qk)
    size = 1 << zoom

    def lon(x):
        return x / size * 360.0 - 180.0

    def lat(y):
        n = math.pi - 2.0 * math.pi * y / size
        return math.degrees(math.atan(math.sinh(n)))

    x0, x1 = lon(tx), lon(tx + 1)
    y0, y1 = lat(ty), lat(ty + 1)
    return (f"POLYGON (({_c(x0)} {_c(y1)}, {_c(x1)} {_c(y1)}, "
            f"{_c(x1)} {_c(y0)}, {_c(x0)} {_c(y0)}, {_c(x0)} {_c(y1)}))")


def _c(x: float) -> str:
    return _n(round(x, 10))
