"""Calendar arithmetic as branch-free integer ops.

The reference leans on Joda-style date libraries for EXTRACT/date_add
(presto-main/.../operator/scalar/DateTimeFunctions.java).  On TPU, calendar
math must be vectorizable pure arithmetic, so this module implements the
standard days<->civil conversion (Howard Hinnant's public-domain "civil"
algorithms) over whole arrays, usable with either numpy or jax.numpy (the
``xp`` parameter).  All inputs/outputs are days since 1970-01-01.
"""

from __future__ import annotations


def civil_from_days(xp, z):
    """days-since-epoch -> (year, month, day), elementwise."""
    z = z + 719468
    era = xp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + xp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def days_from_civil(xp, y, m, d):
    """(year, month, day) -> days-since-epoch, elementwise."""
    y = y - (m <= 2)
    era = xp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = xp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def days_in_month(xp, y, m):
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    feb = xp.where(leap, 29, 28)
    lengths = xp.where(
        (m == 4) | (m == 6) | (m == 9) | (m == 11), 30,
        xp.where(m == 2, feb, 31))
    return lengths


def add_months(xp, days, months):
    """date + INTERVAL MONTH with end-of-month clamping (SQL semantics)."""
    y, m, d = civil_from_days(xp, days)
    m0 = m - 1 + months
    y2 = y + xp.floor_divide(m0, 12)
    m2 = xp.mod(m0, 12) + 1
    d2 = xp.minimum(d, days_in_month(xp, y2, m2))
    return days_from_civil(xp, y2, m2, d2)


def extract_field(xp, days, field: str):
    y, m, d = civil_from_days(xp, days)
    if field == "year":
        return y
    if field == "month":
        return m
    if field == "day":
        return d
    if field == "quarter":
        return (m - 1) // 3 + 1
    if field == "week":
        # ISO week number
        doy_ord = days - days_from_civil(xp, y, xp.ones_like(m), xp.ones_like(d)) + 1
        dow = xp.mod(days + 3, 7) + 1  # 1=Mon..7=Sun (1970-01-01 was Thu)
        week = (doy_ord - dow + 10) // 7
        # weeks 0 / 53 wrap into neighbor years; approximation good enough
        return xp.clip(week, 1, 53)
    if field == "day_of_week" or field == "dow":
        return xp.mod(days + 3, 7) + 1
    if field == "day_of_year" or field == "doy":
        return days - days_from_civil(xp, y, xp.ones_like(m), xp.ones_like(d)) + 1
    raise ValueError(f"unsupported extract field: {field}")
