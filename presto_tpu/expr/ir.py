"""RowExpression IR.

The reference lowers analyzed AST expressions into a small post-analysis IR
(presto-main/.../sql/relational/RowExpression.java:18 — CallExpression,
InputReferenceExpression, ConstantExpression, SpecialForm,
LambdaDefinitionExpression) which the codegen tier consumes.  This is the
same shape: a tiny, typed, channel-indexed expression tree that the
dual-backend compiler (compile.py) consumes.

Special forms exist exactly where evaluation/null semantics differ from
plain function application (short-circuit AND/OR Kleene logic, conditional
CASE/IF/COALESCE, IN's three-valued membership) — mirroring the reference's
SpecialForm.Form list.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

from presto_tpu import types as T


class RowExpression:
    type: T.Type


@dataclasses.dataclass(frozen=True)
class InputRef(RowExpression):
    """Reference to input channel ``index`` (InputReferenceExpression)."""

    index: int
    type: T.Type

    def __str__(self):
        return f"#{self.index}:{self.type.display()}"


@dataclasses.dataclass(frozen=True)
class Constant(RowExpression):
    """A literal in *storage domain* (e.g. decimal as scaled int, date as
    days, varchar as the python string — strings stay host-side)."""

    value: Any  # None == NULL
    type: T.Type

    def __str__(self):
        return f"{self.value!r}:{self.type.display()}"


@dataclasses.dataclass(frozen=True)
class Call(RowExpression):
    """Resolved scalar function application.  ``name`` is the canonical
    function name in the registry; resolution happened already (the
    analyzer/translator picks the overload; fn carries the bound impl)."""

    name: str
    args: Tuple[RowExpression, ...]
    type: T.Type
    fn: Any = dataclasses.field(default=None, compare=False, repr=False)

    def __str__(self):
        return f"{self.name}({', '.join(map(str, self.args))})"


@dataclasses.dataclass(frozen=True)
class SpecialForm(RowExpression):
    """AND / OR / IF / SWITCH / COALESCE / IN.

    - AND, OR: Kleene three-valued logic.
    - IF(cond, a, b): lazy per-position selection.
    - SWITCH(default, (cond1, v1), (cond2, v2), ...): CASE WHEN; args laid
      out [default, cond1, v1, cond2, v2, ...].
    - COALESCE(a, b, ...): first non-null.
    - IN(value, c1, c2, ...): three-valued membership.
    """

    form: str
    args: Tuple[RowExpression, ...]
    type: T.Type

    def __str__(self):
        return f"{self.form}({', '.join(map(str, self.args))})"


@dataclasses.dataclass(frozen=True)
class VarRef(RowExpression):
    """Reference to a lambda parameter (VariableReferenceExpression)."""

    name: str
    type: T.Type

    def __str__(self):
        return self.name


@dataclasses.dataclass(frozen=True)
class LambdaExpr(RowExpression):
    """``(x, y) -> body`` (LambdaDefinitionExpression).

    ``type`` is the body's result type; evaluation happens over the
    flattened element domain of the enclosing array/map function.
    """

    params: Tuple[str, ...]
    param_types: Tuple[T.Type, ...]
    body: RowExpression
    type: T.Type

    def __str__(self):
        return f"({', '.join(self.params)}) -> {self.body}"


def walk(expr: RowExpression):
    """Pre-order traversal."""
    yield expr
    for a in getattr(expr, "args", ()):  # type: ignore[attr-defined]
        yield from walk(a)
    body = getattr(expr, "body", None)
    if body is not None:
        yield from walk(body)


def max_input_channel(expr: RowExpression) -> int:
    mx = -1
    for e in walk(expr):
        if isinstance(e, InputRef):
            mx = max(mx, e.index)
    return mx


def input_channels(expr: RowExpression) -> Tuple[int, ...]:
    seen = []
    for e in walk(expr):
        if isinstance(e, InputRef) and e.index not in seen:
            seen.append(e.index)
    return tuple(sorted(seen))
