"""Dispatcher: async admission for the concurrent serving tier.

Role model: the reference's DispatchManager + queued statement resource
(presto-main/.../dispatcher/DispatchManager.java:59,
QueuedStatementResource.java:86): ``POST /v1/statement`` never plans or
executes inline — it creates a ``DispatchQuery`` in state QUEUED and
returns immediately; a dispatch loop hands the query to resource-group
admission (WAITING_FOR_RESOURCES), and only an admitted query enters the
planning/scheduling/running lifecycle.  Planning and admission therefore
never serialize behind a running query: every statement thread is
per-query, the HTTP handler does no work, and the number of concurrently
*running* queries is exactly what the resource-group tree admits.

Lifecycle (QueryStateMachine role)::

    QUEUED -> WAITING_FOR_RESOURCES -> PLANNING -> SCHEDULING
           -> RUNNING -> FINISHED | FAILED

visible in ``/v1/query/{id}``, ``system.runtime.queries``, and the web
UI.  Admission is arbitrated by ``session.ResourceGroupManager`` (fair /
weighted_fair / query_priority dequeue, per-group ``max_queued`` +
``hard_concurrency_limit``, soft-memory and hard-CPU accounting); a full
queue rejects with the reference's error shape
(``QUERY_QUEUE_FULL`` / ``INSUFFICIENT_RESOURCES``), and ``DELETE`` on a
QUEUED query dequeues it without ever starting execution
(``USER_CANCELED``), still firing ``QueryCompletedEvent``.

Error codes follow the reference's StandardErrorCode layout:
USER_ERROR codes are based at 0x0000_0000 and INSUFFICIENT_RESOURCES at
0x0002_0000.
"""

from __future__ import annotations

import queue
import threading
import uuid
from typing import Dict, Optional, Tuple

from presto_tpu import events as ev
from presto_tpu.server.coordinator import QueryExecution

#: (errorName, errorType, errorCode) triples — the reference's error
#: shape carried in the client-protocol ``error`` object.
USER_CANCELED = ("USER_CANCELED", "USER_ERROR", 0x0000_0003)
QUERY_QUEUE_FULL = ("QUERY_QUEUE_FULL", "INSUFFICIENT_RESOURCES",
                    0x0002_0002)


class DispatchQuery(QueryExecution):
    """One dispatched query: the QUEUED/WAITING_FOR_RESOURCES half of
    the lifecycle wrapped around the inherited execution half.

    Admission happens on this query's own thread (started by the
    dispatch loop), so a statement waiting for a slot costs one parked
    thread and zero planning work; cancellation while queued sets the
    cancel event and wakes the resource-group wait, which dequeues the
    ticket without consuming a slot."""

    def __init__(self, query_id: str, sql: str, coordinator,
                 user: str = "user",
                 session_properties: Optional[Dict[str, str]] = None,
                 catalog: Optional[str] = None,
                 prepared: Optional[Dict[str, str]] = None,
                 trace_token: Optional[str] = None):
        self._cancel_event = threading.Event()
        self._group = None
        super().__init__(query_id, sql, coordinator, user=user,
                         session_properties=session_properties,
                         catalog=catalog, prepared=prepared,
                         trace_token=trace_token, auto_start=False)

    # -- lifecycle ------------------------------------------------------
    def _fail_dispatch(self, message: str,
                       shape: Tuple[str, str, int]) -> None:
        """Terminal failure before execution ever started: no worker
        tasks, no stats — just the error shape, the completion event,
        and an unblocked client.  A shape stamped earlier (the
        low-memory killer / kill_query hitting a still-queued query)
        wins over the generic dispatch shape, same as the error
        message."""
        self.error = self.error or message
        if self.error_name is None:
            self.error_name, self.error_type, self.error_code = shape
        self.state = "FAILED"
        # terminal journal write: a failover must re-serve the
        # rejection, not re-admit the query
        self._journal_terminal()
        self.rows_done.set()
        self._fire_completed()

    def finish_cancelled(self) -> None:
        """Cancelled while still in the dispatch queue (before the
        admission thread started)."""
        self._fail_dispatch("Query was canceled by the user",
                            USER_CANCELED)

    def _run(self) -> None:
        from presto_tpu.session import (
            QueryCancelledError, QueryQueueFullError, Session,
        )

        if self._cancel_event.is_set():
            self.finish_cancelled()
            return
        group = self.co.resource_groups.group_for(
            Session(user=self.user, catalog=self.co.default_catalog))
        self._group = group
        self.resource_group_name = group.name
        try:
            cfg = self._session().effective_config(self.co.config)
        except Exception:  # noqa: BLE001 - bad session property values
            # surface through _run_admitted with its original message;
            # admission itself runs on host defaults
            cfg = self.co.config
        self.state = "WAITING_FOR_RESOURCES"
        try:
            group.acquire(timeout_s=cfg.query_queue_timeout_s,
                          cancel_event=self._cancel_event)
        except QueryCancelledError:
            self._fail_dispatch("Query was canceled by the user",
                                USER_CANCELED)
            return
        except QueryQueueFullError as e:
            self._fail_dispatch(str(e), QUERY_QUEUE_FULL)
            return
        self.admit_time = ev.now()
        self.queued_s = max(self.admit_time - self.create_time, 0.0)
        try:
            if self._cancel_event.is_set():
                self.error = self.error or "Query was canceled by the user"
                if self.error_name is None:
                    self.error_name, self.error_type, self.error_code = \
                        USER_CANCELED
                self.state = "FAILED"
                self._journal_terminal()
                self.rows_done.set()
                return
            self._run_admitted()
        finally:
            self.execution_s = max(ev.now() - self.admit_time, 0.0)
            group.release()
            # CPU accounting: charge the cluster-side work actually done
            # (sum of task wall across the mesh when the rollup reported,
            # else the coordinator-side execution span)
            total_wall_ns = (self.query_stats or {}).get("total_wall_ns", 0)
            group.charge_cpu(total_wall_ns / 1e9 if total_wall_ns
                             else self.execution_s)
            self._fire_completed()

    def cancel(self) -> None:
        """Kill at any lifecycle point: a QUEUED/WAITING_FOR_RESOURCES
        query dequeues without executing (its admission wait wakes and
        raises); a running query gets the inherited worker-task
        fan-out."""
        self.canceled = True
        self._cancel_event.set()
        if self._group is not None:
            self._group.wake()
        if self._tasks_scheduled:
            self._cancel_worker_tasks()


class DispatchManager:
    """The asynchronous dispatch loop: ``submit`` enqueues, the loop
    starts each query's admission thread.  Submission is O(1) for the
    HTTP handler regardless of what the cluster is doing.

    Two execution modes (``dispatcher_pool_size``):

    - **0 (default)**: thread-per-query — the historical behavior,
      byte-identical: the single dispatch loop starts each query's own
      admission thread and total thread count tracks total in-flight
      statements.
    - **> 0**: bounded pool — N drainer threads run admitted queries
      INLINE, so at most N statements are in admission/execution at
      once and a submit burst costs queue entries, not threads.  With
      ``dispatcher_max_queued > 0`` a submit that finds the backlog
      full is SHED immediately: the reference's queue-full shape plus a
      ``Retry-After`` hint scaled to the backlog, so overload degrades
      to fast well-shaped rejections instead of collapse (open-loop
      graceful degradation)."""

    def __init__(self, coordinator):
        self.co = coordinator
        cfg = coordinator.config
        self.pool_size = int(getattr(cfg, "dispatcher_pool_size", 0) or 0)
        self.max_queued = int(getattr(cfg, "dispatcher_max_queued", 0)
                              or 0)
        # statements shed at submit (/metrics:
        # presto_dispatcher_shed_queries_total)
        self.shed_total = 0
        self._shed_lock = threading.Lock()
        # bounded when max_queued > 0: put_nowait + queue.Full make the
        # shed bound exact under concurrent submits (a check-then-put on
        # the approximate qsize() could overshoot it)
        self._queue: "queue.Queue[Optional[DispatchQuery]]" = \
            queue.Queue(maxsize=self.max_queued)
        self._stop = threading.Event()
        # chaos/test hook (coordinator HA): while set, submitted
        # queries stay QUEUED — the deterministic
        # kill-the-coordinator-at-QUEUED shape
        self._paused = threading.Event()
        if self.pool_size > 0:
            self._threads = [
                threading.Thread(target=self._pool_loop, daemon=True,
                                 name=f"dispatcher-{i}")
                for i in range(self.pool_size)]
            for th in self._threads:
                th.start()
        else:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="dispatcher")
            self._thread.start()

    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def submit(self, sql: str, *, user: str = "user",
               session_properties: Optional[Dict[str, str]] = None,
               catalog: Optional[str] = None,
               prepared: Optional[Dict[str, str]] = None,
               trace_token: Optional[str] = None,
               query_id: Optional[str] = None,
               device_checkpoints=None) -> DispatchQuery:
        """``query_id`` is supplied by coordinator-HA adoption (a
        re-queued journaled query keeps its id so client polls find
        it); fresh submissions generate one.  ``device_checkpoints``
        carries the dead primary's journaled boundary checkpoints into
        the requeued execution BEFORE the QUEUED journal write-through,
        so re-admission never wipes mid-program mesh progress."""
        qid = query_id or uuid.uuid4().hex[:16]
        q = DispatchQuery(qid, sql, self.co, user=user,
                          session_properties=session_properties,
                          catalog=catalog, prepared=prepared,
                          trace_token=trace_token)
        if device_checkpoints:
            q._device_ckpts.update(
                {str(k): dict(v) for k, v in device_checkpoints.items()})
        self.co.queries[qid] = q
        # durable journal write-through at QUEUED (server/statestore.py)
        q._journal("QUEUED")
        try:
            self._queue.put_nowait(q)
        except queue.Full:
            # overload shedding: fail fast with the reference's
            # queue-full shape and a retry hint — never an unshaped 500,
            # never an unbounded queue.  The bounded put IS the shed
            # decision, so the backlog cap is exact under concurrent
            # submits; _fail_dispatch's terminal journal write
            # supersedes the QUEUED record above (a failover re-serves
            # the rejection, never re-admits).
            q.retry_after_s = self._retry_after_hint()
            with self._shed_lock:
                self.shed_total += 1
            q._fail_dispatch(
                f"Query queue full: dispatcher backlog is "
                f"{self._queue.qsize()} (max {self.max_queued}); retry "
                f"after {q.retry_after_s}s", QUERY_QUEUE_FULL)
        return q

    def _retry_after_hint(self) -> int:
        """Seconds a shed client should wait: deeper backlog per drainer
        -> longer hint, clamped to [1, 60] so clients neither stampede
        back nor park forever."""
        per = max(self.pool_size, 1)
        return max(1, min(60, 1 + self._queue.qsize() // per))

    def _loop(self) -> None:
        import time

        while not self._stop.is_set():
            if self._paused.is_set():
                time.sleep(0.02)
                continue
            try:
                q = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if q is None:
                return
            # a pause set while get() was already blocking must still
            # hold THIS query (the deterministic kill-at-QUEUED shape)
            while self._paused.is_set() and not self._stop.is_set():
                time.sleep(0.02)
            if self._stop.is_set() or getattr(self.co, "killed", False):
                return
            if q.canceled or q._cancel_event.is_set():
                # DELETE raced the dispatch loop: never start it
                q.finish_cancelled()
                continue
            q._start()

    def _pool_loop(self) -> None:
        """One bounded-pool drainer: identical pause/stop/cancel
        semantics to ``_loop``, but the query runs ON this thread —
        pool_size drainers bound concurrent admission + execution."""
        import time

        while not self._stop.is_set():
            if self._paused.is_set():
                time.sleep(0.02)
                continue
            try:
                q = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if q is None:
                try:   # wake the sibling drainers too; on a full queue
                    # they exit via the 0.2s get timeout + _stop check
                    self._queue.put_nowait(None)
                except queue.Full:
                    pass
                return
            while self._paused.is_set() and not self._stop.is_set():
                time.sleep(0.02)
            if self._stop.is_set() or getattr(self.co, "killed", False):
                return
            if q.canceled or q._cancel_event.is_set():
                q.finish_cancelled()
                continue
            try:
                q._run()
            except Exception:  # noqa: BLE001 - a query never kills a drainer
                pass

    def close(self) -> None:
        self._stop.set()
        try:   # best-effort wake; a full queue falls back to the
            # drainers' 0.2s get timeout + _stop check
            self._queue.put_nowait(None)
        except queue.Full:
            pass
