"""Plan fragmentation: logical plan -> distributed PlanFragments.

Combines the roles of AddExchanges (choosing the inter-node parallelism
strategy per subtree, presto-main/.../optimizations/AddExchanges.java:114)
and PlanFragmenter (cutting the plan at remote exchanges,
presto-main/.../PlanFragmenter.java:88): the optimized single-node plan is
walked bottom-up; aggregations are split into PARTIAL (in the scan
fragment) -> hash exchange on the group keys -> FINAL, equi-joins become
either co-hash-partitioned exchanges (P1/P8) or a broadcast of a small
build side (P2), and everything above the topmost exchange runs in a
SINGLE gather fragment.

Partitioning vocabulary carried on each fragment mirrors
SystemPartitioningHandle.java:49-63: 'source' (leaf scans, split-driven),
'hash' (fixed hash on output channels), 'single' (one task).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.sql.plan import (
    AggregationNode, EnforceSingleRowNode, FilterNode, JoinNode, LimitNode,
    OutputNode, PlanNode, ProjectNode, RemoteMergeNode, RemoteSourceNode,
    SemiJoinNode, SortNode, TableFinishNode, TableScanNode,
    TableWriterNode, UnionNode, UnnestNode,
    ValuesNode, WindowNode,
)


@dataclasses.dataclass
class PlanFragment:
    """One stage of the distributed plan (PlanFragment analogue).

    ``partitioning``: how tasks of this fragment are placed —
      'source' = one task per worker, driven by connector splits;
      'hash'   = fixed task count, input hash-partitioned;
      'single' = exactly one task (gather).
    ``output_partitioning``: how this fragment's output is routed to the
    consumer — ('hash', channels) / ('single', ()) / ('broadcast', ()).
    """

    fragment_id: int
    root: PlanNode
    partitioning: str
    output_partitioning: Tuple[str, Tuple[int, ...]]
    consumed_fragments: Tuple[int, ...]
    # 'scaled' fragments only: estimated input rows, so the scheduler can
    # size the writer-task count to the data volume
    # (ScaledWriterScheduler role, statically decided)
    scale_rows: Optional[float] = None
    # every fragment this one TRANSITIVELY consumes — the producer
    # subtree whole-stage retry re-creates when a non-leaf task of this
    # fragment is lost (the Presto-on-Spark re-run unit)
    producer_subtree: Tuple[int, ...] = ()
    # device-sharded exchange annotation (mesh_device_exchange): can the
    # boundary this fragment's output crosses lower to an in-program
    # collective (all_to_all / all_gather / gather) when producer and
    # consumer are co-resident on one device mesh?  None = not yet
    # computed (annotate_device_exchange fills it); False boundaries
    # keep the HTTP plane even on a co-resident mesh.
    device_exchange_eligible: Optional[bool] = None


@dataclasses.dataclass
class DistributedPlan:
    fragments: List[PlanFragment]          # topological: producers first
    root_fragment_id: int
    column_names: List[str]
    column_types: List[T.Type]


class Fragmenter:
    """One instance per query."""

    def __init__(self, broadcast_row_limit: Optional[int] = None,
                 metadata=None, config=None):
        from presto_tpu.config import DEFAULT

        self.config = config or DEFAULT
        self.broadcast_row_limit = (
            broadcast_row_limit if broadcast_row_limit is not None
            else self.config.broadcast_join_row_limit)
        self.metadata = metadata
        self.fragments: List[PlanFragment] = []
        self._stats_calculator = None  # one memoized derivation per query

    def fragment(self, root: OutputNode) -> DistributedPlan:
        node, child_frags = self._visit(root.source)
        # everything left runs in the SINGLE gather fragment
        fid = self._add(node, "single", ("single", ()), child_frags)
        return DistributedPlan(self.fragments, fid,
                               [n for n, _ in root.columns],
                               [t for _, t in root.columns])

    def _add(self, root: PlanNode, partitioning: str,
             output_partitioning: Tuple[str, Tuple[int, ...]],
             consumed: Sequence[int]) -> int:
        fid = len(self.fragments)
        # fragments list is topological (producers first), so every
        # consumed fragment's subtree is already final
        subtree: List[int] = []
        for c in consumed:
            for p in self.fragments[c].producer_subtree + (c,):
                if p not in subtree:
                    subtree.append(p)
        self.fragments.append(PlanFragment(
            fid, root, partitioning, output_partitioning, tuple(consumed),
            producer_subtree=tuple(sorted(subtree))))
        return fid

    # ------------------------------------------------------------------
    # Visitor: returns (node-for-current-fragment, consumed fragment ids).
    # A returned RemoteSourceNode means the subtree was cut into its own
    # fragment(s).
    # ------------------------------------------------------------------
    def _visit(self, node: PlanNode) -> Tuple[PlanNode, List[int]]:
        if isinstance(node, TableFinishNode):
            return self._visit_table_finish(node)
        if isinstance(node, AggregationNode):
            return self._visit_aggregation(node)
        if isinstance(node, JoinNode):
            return self._visit_join(node)
        if isinstance(node, SemiJoinNode):
            return self._visit_semijoin(node)
        if isinstance(node, SortNode):
            return self._visit_sort(node, limit=None)
        if isinstance(node, LimitNode) and isinstance(node.source,
                                                     SortNode):
            return self._visit_sort(node.source, limit=node.count)
        if isinstance(node, UnionNode):
            return self._visit_union(node)
        if isinstance(node, (FilterNode, ProjectNode, LimitNode, SortNode,
                             WindowNode, EnforceSingleRowNode,
                             UnnestNode)):
            # stays in the consumer fragment; recurse into sources
            new_sources = []
            consumed: List[int] = []
            for s in node.sources:
                ns, c = self._visit(s)
                new_sources.append(ns)
                consumed += c
            return _replace_sources(node, new_sources), consumed
        # leaves (TableScan, Values) stay put
        return node, []

    def _visit_table_finish(self, node) -> Tuple[PlanNode, List[int]]:
        """Distributed DML (P6, scaled writers): the query subtree becomes
        its own fragment with round-robin ('arbitrary') output feeding a
        'scaled'-partitioned writer fragment whose task count the
        scheduler sizes to the estimated volume
        (SCALED_WRITER_DISTRIBUTION, SystemPartitioningHandle.java:62 +
        ScaledWriterScheduler.java:40); the TableFinish commit stays in
        the single root fragment."""
        writer: TableWriterNode = node.source
        src, consumed = self._visit(writer.source)
        est = None
        try:
            est = self._estimate_rows(writer.source)
        except Exception:  # noqa: BLE001 - stats are advisory
            pass
        fid_src = self._source_fragment(src, consumed, ("arbitrary", ()))
        remote = RemoteSourceNode((fid_src,),
                                  tuple(writer.source.columns))
        w = TableWriterNode(remote, writer.catalog, writer.table,
                            writer.write_id, writer.columns)
        fid_w = self._add(w, "scaled", ("single", ()), [fid_src])
        self.fragments[fid_w].scale_rows = est
        remote_w = RemoteSourceNode((fid_w,), tuple(writer.columns))
        finish = TableFinishNode(remote_w, node.catalog, node.table,
                                 node.write_id, node.columns)
        return finish, [fid_w]

    def _visit_union(self, node: UnionNode) -> Tuple[PlanNode, List[int]]:
        """UNION ALL branches with their own scans become source fragments
        with round-robin ('arbitrary') output — P3, the
        FIXED_ARBITRARY_DISTRIBUTION / ArbitraryOutputBuffer shape — so
        each branch's scan parallelizes instead of the whole union
        running in one task.  Branches without scans stay local."""
        fids: List[int] = []
        local_inputs: List[PlanNode] = []
        consumed: List[int] = []
        for inp in node.inputs:
            src, c = self._visit(inp)
            if _has_scan(src) and self._parallel_safe(src):
                fid = self._source_fragment(src, c, ("arbitrary", ()))
                fids.append(fid)
                consumed.append(fid)
            else:
                local_inputs.append(src)
                consumed += c
        if not fids:
            return _replace_sources(node, local_inputs), consumed
        remote = RemoteSourceNode(tuple(fids), tuple(node.columns))
        if not local_inputs:
            return remote, consumed
        return (UnionNode(tuple([remote] + local_inputs), node.columns),
                consumed)

    def _visit_sort(self, node: SortNode, limit) -> Tuple[PlanNode,
                                                          List[int]]:
        """Distributed ORDER BY / TopN (MergeOperator.java:45 pattern):
        each producer task sorts (and truncates) its share; the consumer
        k-way merges the pre-sorted streams instead of re-sorting
        everything on one node.  Falls back to a consumer-side full sort
        when the subtree cannot safely run as a multi-task fragment."""
        src, consumed = self._visit(node.source)
        if not self._parallel_safe(src):
            inner = SortNode(src, node.sort_keys)
            out: PlanNode = (LimitNode(inner, limit)
                             if limit is not None else inner)
            return out, consumed
        partial: PlanNode = SortNode(src, node.sort_keys)
        if limit is not None:
            partial = LimitNode(partial, limit)   # TopN fuses per task
        fid = self._source_fragment(partial, consumed, ("single", ()),
                                    check=src)
        merge = RemoteMergeNode((fid,), node.sort_keys,
                                tuple(node.columns), limit)
        return merge, [fid]

    def _parallel_safe(self, node: PlanNode) -> bool:
        """True when this consumer-fragment subtree can be replicated
        into N tasks without changing results: at most one table scan
        (split-sharded), no global aggregation / window / values /
        single-row enforcement / cross join, whose per-task replication
        would duplicate or starve rows."""
        scans = 0
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, TableScanNode):
                scans += 1
            elif isinstance(n, AggregationNode) and not n.group_channels \
                    and n.step != "partial":
                # a PARTIAL global aggregation replicates fine: each task
                # emits one component row and the FINAL stage merges them
                return False
            elif isinstance(n, (WindowNode, EnforceSingleRowNode,
                                UnionNode, LimitNode)):
                # an inner LIMIT replicated into N tasks would emit up
                # to N*limit rows
                return False
            elif isinstance(n, RemoteMergeNode):
                # an ordered merge (possibly limited) must run once
                return False
            elif isinstance(n, ValuesNode):
                return False
            elif isinstance(n, JoinNode) and (n.kind == "cross"
                                              or not n.left_keys):
                return False
            stack.extend(n.sources)
        return scans <= 1

    def _source_fragment(self, node: PlanNode,
                         consumed: Sequence[int],
                         output: Tuple[str, Tuple[int, ...]],
                         check: Optional[PlanNode] = None) -> int:
        """Cut ``node`` into its own fragment.  Fragments containing a
        table scan are 'source'-partitioned (split-driven); fragments fed
        only by exchanges are 'hash'-partitioned.  A subtree that cannot
        be replicated into N tasks without changing results (cross join,
        inner LIMIT, VALUES, scalar-subquery guard...) runs as a
        'single'-task fragment — its output exchange still routes
        normally.  ``check`` overrides which subtree the safety test sees
        (a partial-aggregation wrapper is safe even when the bare partial
        node would not be)."""
        if not self._parallel_safe(check if check is not None else node):
            part = "single"
        else:
            part = "source" if _has_scan(node) else "hash"
        return self._add(node, part, output, consumed)

    def _visit_aggregation(self, node: AggregationNode):
        src, consumed = self._visit(node.source)
        if node.step != "single":
            # already split by the logical tier (partial-agg-through-
            # union rule): a FINAL merges wherever its input lands after
            # a hash exchange on the keys; a PARTIAL stays in place
            if node.step == "final" and node.group_channels:
                fid = self._source_fragment(
                    src, consumed, ("hash", tuple(node.group_channels)))
                remote = RemoteSourceNode((fid,),
                                          tuple(node.source.columns))
                return _replace_sources(node, [remote]), [fid]
            return _replace_sources(node, [src]), consumed
        if not self.config.partial_aggregation_enabled:
            # partial_aggregation_enabled=false: single-step aggregation
            # after a hash exchange on the group keys (or at the gather
            # fragment for global aggregates)
            if not node.group_channels:
                return _replace_sources(node, [src]), consumed
            fid = self._source_fragment(
                src, consumed, ("hash", tuple(node.group_channels)))
            remote = RemoteSourceNode((fid,), tuple(node.source.columns))
            return _replace_sources(node, [remote]), [fid]
        if any(a.distinct for a in node.aggregates):
            # distinct aggs need every row of a group on one node; hash
            # exchange on the group keys then single-step aggregate
            if not node.group_channels:
                return _replace_sources(node, [src]), consumed
            fid = self._source_fragment(
                src, consumed, ("hash", tuple(node.group_channels)))
            remote = RemoteSourceNode((fid,), tuple(node.source.columns))
            return _replace_sources(node, [remote]), [fid]

        # PARTIAL in the producer fragment
        ngroups = len(node.group_channels)
        comp_cols: List[Tuple[str, T.Type]] = [
            node.columns[i] for i in range(ngroups)]
        ci = 0
        for agg in node.aggregates:
            for prim, ctype in agg.spec.components:
                comp_cols.append((f"$comp{ci}", ctype))
                ci += 1
        partial = AggregationNode(src, node.group_channels, node.aggregates,
                                  tuple(comp_cols), step="partial")
        if ngroups:
            out = ("hash", tuple(range(ngroups)))
        else:
            out = ("single", ())
        fid = self._source_fragment(partial, consumed, out, check=src)
        remote = RemoteSourceNode((fid,), tuple(comp_cols))
        final = AggregationNode(remote, tuple(range(ngroups)),
                                node.aggregates, node.columns, step="final")
        return final, [fid]

    def _estimate_rows(self, node: PlanNode) -> float:
        try:
            from presto_tpu.sql.optimizer import _estimate_rows
            from presto_tpu.sql.stats import StatsCalculator

            if self._stats_calculator is None:
                self._stats_calculator = StatsCalculator(self.metadata)
            return _estimate_rows(node, self.metadata,
                                  self._stats_calculator)
        except Exception:
            return float("inf")

    def _visit_join(self, node: JoinNode):
        if node.kind == "cross" or not node.left_keys:
            # cross joins gather to the single fragment
            left, lc = self._visit(node.left)
            right, rc = self._visit(node.right)
            return _replace_sources(node, [left, right]), lc + rc
        left, lc = self._visit(node.left)
        right, rc = self._visit(node.right)

        # join_distribution_type session property forces a distribution;
        # otherwise a memo-annotated join (DetermineJoinDistribution,
        # sql/memo.py) carries its cost-chosen placement; otherwise the
        # stats threshold decides (DetermineJoinDistributionType role)
        dist = self.config.join_distribution_type
        if dist != "automatic":
            broadcast = dist == "broadcast"
        elif node.distribution is not None:
            broadcast = node.distribution == "replicated"
        else:
            broadcast = (self._estimate_rows(node.right)
                         <= self.broadcast_row_limit)
        if broadcast:
            # P2: broadcast the small build side into every probe task;
            # probe stays in ITS OWN fragment (no exchange for probe rows)
            rfid = self._source_fragment(
                right, rc, ("broadcast", ()))
            remote_r = RemoteSourceNode((rfid,), tuple(node.right.columns))
            return (_replace_sources(node, [left, remote_r]), lc + [rfid])

        # P1/P8: co-hash-partition both sides on the join keys
        lfid = self._source_fragment(
            left, lc, ("hash", tuple(node.left_keys)))
        rfid = self._source_fragment(
            right, rc, ("hash", tuple(node.right_keys)))
        remote_l = RemoteSourceNode((lfid,), tuple(node.left.columns))
        remote_r = RemoteSourceNode((rfid,), tuple(node.right.columns))
        return (_replace_sources(node, [remote_l, remote_r]),
                [lfid, rfid])

    def _visit_semijoin(self, node: SemiJoinNode):
        src, sc = self._visit(node.source)
        filt, fc = self._visit(node.filtering)
        # filtering side is usually small: broadcast it
        ffid = self._source_fragment(filt, fc, ("broadcast", ()))
        remote_f = RemoteSourceNode((ffid,), tuple(node.filtering.columns))
        return _replace_sources(node, [src, remote_f]), sc + [ffid]


def annotate_device_exchange(dplan: "DistributedPlan") -> bool:
    """Per-boundary device-exchange eligibility (the mesh_device_exchange
    planning half): a fragment's output boundary can lower to an
    in-program collective when its subtree is inside the mesh tier's
    supported subset (parallel/sqlmesh._check_supported) AND its output
    partitioning has a collective lowering ('hash' -> all_to_all,
    'broadcast'/'single' -> all_gather/gather, 'arbitrary' -> rotated
    all_to_all).  Scans of coordinator-local-only connectors (the
    system catalog: live data exists only on the node serving it) are
    never eligible.  Returns True when EVERY boundary qualifies — the
    whole fragment DAG can then run as one SPMD program; any False
    keeps the query on the HTTP plane (per-boundary mixing would leave
    device arrays with no wire to cross).  Idempotent; annotations are
    cached on the fragments (plan-cache hits keep them)."""
    from presto_tpu.parallel.sqlmesh import MeshUnsupported, _check_supported

    if dplan.fragments and dplan.fragments[0].device_exchange_eligible \
            is not None:
        return all(f.device_exchange_eligible for f in dplan.fragments)
    ok_all = True
    for f in dplan.fragments:
        ok = f.output_partitioning[0] in ("hash", "broadcast", "single",
                                          "arbitrary")
        if ok:
            try:
                _check_supported(f.root)
            except (MeshUnsupported, NotImplementedError):
                ok = False
        if ok and any(s.catalog == "system"
                      for s in _scans(f.root)):
            ok = False
        if ok and _has_writer(f.root):
            # DML fragments commit through worker-side TableWriter
            # tasks; the collective tier is a query-only fast path
            ok = False
        f.device_exchange_eligible = ok
        ok_all = ok_all and ok
    return ok_all


def _scans(node: PlanNode):
    if isinstance(node, TableScanNode):
        yield node
    for s in node.sources:
        yield from _scans(s)


def _has_writer(node: PlanNode) -> bool:
    if isinstance(node, (TableWriterNode, TableFinishNode)):
        return True
    return any(_has_writer(s) for s in node.sources)


def _has_scan(node: PlanNode) -> bool:
    if isinstance(node, TableScanNode):
        return True
    return any(_has_scan(s) for s in node.sources)


def _replace_sources(node: PlanNode, sources: List[PlanNode]) -> PlanNode:
    if not sources:
        return node
    fields: Dict[str, object] = {}
    names = [f.name for f in dataclasses.fields(node)]
    if "left" in names:
        fields["left"] = sources[0]
        fields["right"] = sources[1]
    elif "filtering" in names:
        fields["source"] = sources[0]
        fields["filtering"] = sources[1]
    elif "inputs" in names:
        fields["inputs"] = tuple(sources)
    elif "source" in names:
        fields["source"] = sources[0]
    return dataclasses.replace(node, **fields)
