"""Worker HTTP server: the TaskResource surface.

Routes mirror the reference's worker REST API
(presto-main/.../server/TaskResource.java:83-84,121-124,240-244):

    POST   /v1/task/{taskId}                      create/update task
    GET    /v1/task/{taskId}                      task info/status
    DELETE /v1/task/{taskId}                      cancel
    GET    /v1/task/{taskId}/results/{buffer}/{token}   page fetch + ack
    GET    /v1/info                               node info (heartbeat ping)

Control bodies are JSON fragment descriptors (TaskUpdateRequest-style; the
in-process DistributedQueryRunner pattern); data responses are raw
concatenated wire frames (presto_tpu.serde) with token bookkeeping in
headers — the PRESTO_PAGES content-type role.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from presto_tpu.config import DEFAULT, EngineConfig
from presto_tpu.connectors.api import ConnectorRegistry
from presto_tpu.server.task import SqlTaskManager


class WorkerServer:
    def __init__(self, registry: ConnectorRegistry,
                 config: EngineConfig = DEFAULT, port: int = 0,
                 node_id: str = "worker",
                 internal_secret: Optional[str] = None,
                 location: str = "",
                 fault_injector=None, http_client=None,
                 drain_grace_s: float = 2.0,
                 announce_to: Optional[list] = None,
                 announce_interval_s: float = 1.0):
        from presto_tpu.server.errortracker import RetryingHttpClient
        from presto_tpu.server.security import InternalAuthenticator
        from presto_tpu.server.spool import make_spool_store

        self.node_id = node_id
        # topology label (rack/zone) announced to the
        # coordinator for TopologyAwareNodeSelector placement
        self.location = location
        # device-mesh identity announced to the coordinator: placements
        # sharing a fingerprint (and the coordinator's own) are
        # co-resident on one jax mesh, enabling the device-sharded
        # exchange tier (mesh_device_exchange)
        from presto_tpu.parallel.mesh import mesh_fingerprint

        self.mesh_fingerprint = mesh_fingerprint()
        self.internal_auth = (InternalAuthenticator(internal_secret)
                              if internal_secret else None)
        # chaos substrate hook (server/faults.py): consulted before every
        # request is dispatched; None in production
        self.fault_injector = fault_injector
        # node-wide error-tracked HTTP client: this worker's remote-source
        # fetches retry transient producer failures with backoff
        self.http = http_client or RetryingHttpClient(
            max_error_duration_s=config.remote_request_max_error_duration_s,
            min_backoff_s=config.remote_request_min_backoff_s,
            max_backoff_s=config.remote_request_max_backoff_s)
        # spooled exchange tier: the store is always constructed (dirs
        # are created lazily on first write) so a SET SESSION toggle can
        # enable spooling per query; exchange_spooling_enabled gates use
        self.spool = make_spool_store(config, injector=fault_injector)
        self.task_manager = SqlTaskManager(
            registry, config,
            fetch_headers=(self.internal_auth.header()
                           if self.internal_auth else None),
            http_client=self.http, spool=self.spool,
            fault_injector=fault_injector)
        # graceful shutdown (GracefulShutdownHandler.java role): once
        # draining, new tasks are refused, /v1/info advertises
        # SHUTTING_DOWN so the coordinator stops scheduling here, and
        # close() waits for running tasks to finish.  PUT /v1/info/state
        # additionally starts the drain-and-remove sequence after a
        # grace period (the reference sleeps its gracePeriod twice) —
        # with spooling on, finished tasks' output is durable in the
        # spool, so the worker exits without waiting for consumers.
        self.draining = False
        self.drain_grace_s = drain_grace_s
        self._drain_thread: Optional[threading.Thread] = None
        worker = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _json(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _fault(self, method: str) -> bool:
                """True when the injector consumed this request (the
                chaos hook: http-503 answered or connection dropped)."""
                inj = worker.fault_injector
                if inj is None:
                    return False
                hit = inj.apply_server(self.path, method)
                if hit is None:
                    return False
                policy, rule = hit
                if policy == "http-503":
                    self._json(rule.status, {"error": "injected fault"})
                else:  # drop-connection: no response bytes at all
                    self.close_connection = True
                return True

            def _internal_ok(self, parts) -> bool:
                """Everything under /v1/task and /v1/query (create,
                status, results, cancel) requires the cluster token when
                one is set; the /v1/info health probe stays open."""
                if worker.internal_auth is None or \
                        parts[:2] not in (["v1", "task"], ["v1", "query"]):
                    return True
                from presto_tpu.server.security import (
                    InternalAuthenticator,
                )

                if worker.internal_auth.verify(self.headers.get(
                        InternalAuthenticator.HEADER)):
                    return True
                self._json(401, {"error": "unauthenticated internal "
                                          "request"})
                return False

            def do_GET(self):  # noqa: N802
                if self._fault("GET"):
                    return
                parts = self.path.strip("/").split("/")
                if parts[:2] == ["v1", "info"]:
                    self._json(200, {
                        "nodeId": worker.node_id,
                        "state": ("SHUTTING_DOWN" if worker.draining
                                  else "ACTIVE"),
                        # live MemoryInfo rides the health surface so
                        # any poller sees pool pressure without the
                        # authenticated /v1/memory endpoint
                        "memoryInfo":
                            worker.task_manager.memory_info()})
                    return
                if parts == ["metrics"]:
                    # Prometheus text plane (server/metrics.py); open
                    # like /v1/info — it exposes counters, never SQL,
                    # plans, or rows
                    from presto_tpu.server.metrics import worker_metrics

                    body = worker_metrics(worker).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parts == ["v1", "memory"]:
                    if not self._internal_ok(["v1", "task"]):
                        return
                    self._json(200, worker.task_manager.memory_info())
                    return
                if not self._internal_ok(parts):
                    return
                if parts == ["v1", "task"]:
                    self._json(200, worker.task_manager.list_infos())
                    return
                if parts[:2] == ["v1", "task"] and len(parts) == 3:
                    task = worker.task_manager.get(parts[2])
                    if task is None:
                        self._json(404, {"error": "no such task"})
                        return
                    self._json(200, task.info())
                    return
                if (parts[:2] == ["v1", "task"] and len(parts) == 6
                        and parts[3] == "results"):
                    self._results(parts[2], int(parts[4]), int(parts[5]))
                    return
                self._json(404, {"error": f"bad path {self.path}"})

            def _results(self, task_id: str, buffer_id: int,
                         token: int) -> None:
                task = worker.task_manager.get(task_id)
                if task is None:
                    self._json(404, {"error": "no such task"})
                    return
                try:
                    pages, next_token, complete = task.buffers.get_pages(
                        buffer_id, token, wait_s=1.0)
                except Exception as e:  # noqa: BLE001
                    self._json(500, {"error": str(e)})
                    return
                body = b"".join(pages)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-presto-pages")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("X-Presto-Next-Token", str(next_token))
                self.send_header("X-Presto-Buffer-Complete",
                                 "true" if complete else "false")
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                if self._fault("POST"):
                    return
                parts = self.path.strip("/").split("/")
                # intra-cluster auth: a worker only executes plans from
                # peers holding the shared-secret token
                # (InternalAuthenticationManager role)
                if not self._internal_ok(parts):
                    return
                if (parts[:2] == ["v1", "task"] and len(parts) == 4
                        and parts[3] == "coordinator"):
                    # coordinator HA re-attach: a standby that adopted
                    # this task's query on failover announces itself as
                    # the owning coordinator.  The task is untouched —
                    # it keeps producing into the spool; the response
                    # carries enough state for the standby to decide
                    # re-attach vs spool-repoint vs restart.
                    task = worker.task_manager.get(parts[2])
                    if task is None:
                        self._json(404, {"error": "no such task"})
                        return
                    n = int(self.headers.get("Content-Length", 0))
                    try:
                        req = json.loads(self.rfile.read(n) or b"{}")
                        task.coordinator_uri = str(
                            req.get("coordinator") or "")
                    except ValueError as e:
                        self._json(400, {"error": f"bad repoint: {e}"})
                        return
                    self._json(200, {
                        "status": "reattached",
                        "state": task.state,
                        "pagesEnqueued": task.buffers.pages_enqueued,
                        "spooledComplete":
                            task.buffers.spooled_complete()})
                    return
                if (parts[:2] == ["v1", "task"] and len(parts) == 4
                        and parts[3] == "remote-sources"):
                    # mid-query task recovery: repoint this task's
                    # remote-source fetches at a replacement producer.
                    # Allowed while draining — it keeps queries already
                    # running here alive.
                    task = worker.task_manager.get(parts[2])
                    if task is None:
                        self._json(404, {"error": "no such task"})
                        return
                    n = int(self.headers.get("Content-Length", 0))
                    try:
                        req = json.loads(self.rfile.read(n))
                        old = str(req["old_prefix"])
                        probe = bool(req.get("probe", False))
                        # spool=true: same-attempt repoint at the dead
                        # producer's spooled output (token preserved,
                        # no delivered guard)
                        spool = bool(req.get("spool", False))
                        new = "" if probe else str(req["new_prefix"])
                    except (KeyError, TypeError, ValueError) as e:
                        self._json(400, {"error": f"bad repoint: {e}"})
                        return
                    if probe:
                        # read-only delivery probe: whole-stage retry
                        # sizes its restart cascade with this before
                        # mutating any source
                        status = task.probe_remote_source(old)
                    else:
                        status = task.repoint_remote_source(
                            old, new, spool=spool)
                    self._json(200, {"status": status})
                    return
                if parts[:2] == ["v1", "task"] and worker.draining:
                    self._json(503, {"error": "worker is shutting down"})
                    return
                if parts[:2] == ["v1", "task"] and len(parts) == 3:
                    from presto_tpu.sql.planserde import (
                        PlanSerdeError, fragment_from_json,
                    )

                    n = int(self.headers.get("Content-Length", 0))
                    try:
                        req = json.loads(self.rfile.read(n))
                        fragment = fragment_from_json(req["fragment"])
                        scan_shard = tuple(req["scan_shard"])
                        remote_sources = {int(fid): us for fid, us in
                                          req["remote_sources"].items()}
                        n_out = int(req["n_output_partitions"])
                        broadcast = bool(req["broadcast_output"])
                        session_props = dict(
                            req.get("session_properties") or {})
                        # query trace token: body field, with the header
                        # as fallback (TraceTokenModule role)
                        trace_token = str(
                            req.get("trace_token")
                            or self.headers.get("X-Presto-Trace-Token")
                            or "")
                        # coordinator stats-epoch snapshot keying the
                        # worker-side plan_fragment cache
                        plan_epochs = req.get("plan_epochs") or None
                    except (PlanSerdeError, KeyError, TypeError,
                            AttributeError, ValueError) as e:
                        self._json(400, {"error": f"bad task update: {e}"})
                        return
                    try:
                        task = worker.task_manager.create_task(
                            task_id=parts[2],
                            fragment=fragment,
                            scan_shard=scan_shard,
                            remote_sources=remote_sources,
                            n_output_partitions=n_out,
                            broadcast_output=broadcast,
                            session_properties=session_props,
                            trace_token=trace_token,
                            plan_epochs=plan_epochs)
                    except Exception as e:  # noqa: BLE001 - bad props
                        self._json(400, {"error": f"bad task update: {e}"})
                        return
                    self._json(200, task.info())
                    return
                self._json(404, {"error": f"bad path {self.path}"})

            def do_PUT(self):  # noqa: N802
                if self._fault("PUT"):
                    return
                parts = self.path.strip("/").split("/")
                if not self._internal_ok(["v1", "task"]):
                    return
                if parts == ["v1", "info", "state"]:
                    # PUT "SHUTTING_DOWN" starts a graceful drain
                    # (the reference's /v1/info/state shutdown trigger):
                    # refuse new tasks immediately, then — after a grace
                    # period that lets the coordinator observe the state
                    # and repoint consumers at the spool — wait out
                    # running tasks and leave the cluster
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n).decode().strip().strip('"')
                    if body != "SHUTTING_DOWN":
                        self._json(400, {"error": f"bad state {body!r}"})
                        return
                    worker.draining = True
                    worker._start_drain()
                    self._json(200, {"state": "SHUTTING_DOWN"})
                    return
                self._json(404, {"error": f"bad path {self.path}"})

            def do_DELETE(self):  # noqa: N802
                if self._fault("DELETE"):
                    return
                parts = self.path.strip("/").split("/")
                if not self._internal_ok(parts):
                    return
                if parts[:2] == ["v1", "task"] and len(parts) == 3:
                    task = worker.task_manager.get(parts[2])
                    if task is not None:
                        task.cancel()
                    self._json(200, {"canceled": True})
                    return
                if parts[:2] == ["v1", "query"] and len(parts) == 3:
                    n = worker.task_manager.cancel_query(parts[2])
                    self._json(200, {"canceledTasks": n})
                    return
                self._json(404, {"error": f"bad path {self.path}"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"worker-http-{self.port}")
        self._thread.start()
        # stateless announcer (coordinator HA): re-announce this node
        # to EVERY configured coordinator — primary and standby alike —
        # so a standby that takes over already knows the live cluster
        self.announce_to = list(announce_to or [])
        self._announce_stop = threading.Event()
        if self.announce_to:
            threading.Thread(
                target=self._announce_loop,
                args=(max(announce_interval_s, 0.1),),
                daemon=True,
                name=f"announce-{self.node_id}").start()

    def announce_once(self) -> None:
        """One announcement round to every configured coordinator
        (best-effort per target: a dead primary must not stop the
        standby from hearing about this node)."""
        import urllib.request

        body = json.dumps({
            "nodeId": self.node_id, "uri": self.uri,
            "location": self.location,
            "meshFingerprint": self.mesh_fingerprint,
            # MemoryInfo rides announcements: the coordinator's memory
            # tick folds it without waiting for its own poll round
            "memoryInfo": self.task_manager.memory_info()}).encode()
        headers = {"Content-Type": "application/json"}
        if self.internal_auth is not None:
            headers.update(self.internal_auth.header())
        for target in self.announce_to:
            try:
                req = urllib.request.Request(
                    f"{target}/v1/announcement", data=body,
                    method="POST", headers=dict(headers))
                with urllib.request.urlopen(req, timeout=5):
                    pass
            except Exception:  # noqa: BLE001 - a target may be down
                pass

    def _announce_loop(self, interval_s: float) -> None:
        self.announce_once()
        while not self._announce_stop.wait(interval_s):
            if self.draining:
                return
            self.announce_once()

    def _start_drain(self) -> None:
        """Background drain-and-remove (the PUT /v1/info/state role):
        grace sleep, then the full graceful shutdown."""
        import time

        if self._drain_thread is not None:
            return

        def drain():
            time.sleep(self.drain_grace_s)
            self.shutdown_gracefully()

        self._drain_thread = threading.Thread(
            target=drain, daemon=True,
            name=f"drain-{self.node_id}")
        self._drain_thread.start()

    def shutdown_gracefully(self, drain_timeout_s: float = 30.0) -> None:
        """Stop accepting tasks, wait for running ones, then close
        (GracefulShutdownHandler drain sequence)."""
        import time

        self.draining = True
        deadline = time.monotonic() + drain_timeout_s
        # wait for tasks to finish AND for their output to be safe:
        # either consumers fetched it, or (spooled exchange) the whole
        # output is durable in the spool and consumers re-pull it from
        # there — closing earlier would destroy pages a downstream
        # stage still needs
        while (self.task_manager.undrained_count() > 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
        self.close()

    def close(self) -> None:
        self._announce_stop.set()
        self.task_manager.cancel_all()
        self.spool.close()
        self._httpd.shutdown()
        self._httpd.server_close()
