"""Coordinator HA: a durable query-state journal + a takeover lease.

SURVEY §5.3 names the coordinator the reference's last single point of
failure ("Checkpointing/restart: none for queries", §5.4): every
worker-side failure is recoverable (stage retry, spool repoint,
speculation, drain), but a coordinator crash loses every in-flight
query.  This module closes that asymmetry with two small durable
structures over the SAME pluggable object API the spool's object tier
uses (``spool.LocalObjectApi`` — a real S3/GCS client drops in behind
the same methods):

- **QueryStateStore** — one JSON object per query
  (``queries/{query_id}``), write-through at lifecycle transitions:
  normalized SQL + session/catalog fingerprint, the serde'd fragmented
  ``DistributedPlan``, task placements + attempt ids, root-drain
  consumed tokens, result adoption ids, and terminal status.  A standby
  coordinator ADOPTS the journal on failover: FINISHED queries serve
  straight from their adopted spool pages, RUNNING queries re-attach to
  live tasks (or repoint/restart through the existing spool-recovery
  machinery), QUEUED queries re-enter admission.

- **CoordinatorLease** — the mutual-exclusion heartbeat: one ``lease``
  object ``{owner, generation, expires_at}`` renewed by the active
  coordinator every ``ttl/3``; a standby that observes the lease
  expired claims the NEXT generation via an atomic create-if-absent
  (``claim-{generation:08d}``, the compare-and-swap) — exactly one of
  N racing standbys wins, and the loser keeps watching.

Journal writes are strictly best-effort on the primary (a journal
problem must never fail a query the engine can run); adoption on the
standby verifies everything it reads (a stream that is not complete in
the spool restarts through stage retry, never serves partial rows).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from presto_tpu.server.spool import LocalObjectApi

#: journal object keys live under one prefix so a state root can share
#: a bucket with the spool's object tier
_QUERY_PREFIX = "queries/"
_LEASE_KEY = "lease"
_CLAIM_PREFIX = "claim-"


@dataclasses.dataclass
class QueryJournal:
    """One query's durable state — everything a standby needs to adopt
    it at any lifecycle point.  ``state`` is the last journaled
    lifecycle state, which may trail the live one by one transition
    (writes happen AT transitions)."""

    query_id: str
    sql: str
    user: str = "user"
    catalog: Optional[str] = None
    session_properties: Dict[str, str] = dataclasses.field(
        default_factory=dict)
    prepared: Dict[str, str] = dataclasses.field(default_factory=dict)
    trace_token: str = ""
    plan_key_sql: Optional[str] = None
    state: str = "QUEUED"
    error: Optional[str] = None
    create_time: float = 0.0
    # serde'd DistributedPlan (sql/planserde.dplan_to_json), present
    # once planning finished
    dplan: Optional[Dict[str, Any]] = None
    # (fragment_id, task_id, worker_uri) per scheduled task — the live
    # placements at the last journal write (attempt-qualified ids)
    placements: List[Tuple[int, str, str]] = dataclasses.field(
        default_factory=list)
    # base task id -> attempt counter (fresh attempts on the standby
    # continue from here, so ids never collide with superseded ones)
    attempts: Dict[str, int] = dataclasses.field(default_factory=dict)
    # task id -> minimal recreate spec {fid,index,scan_shard,n_out,
    # broadcast,consumer_index,base}; the fragment itself comes from
    # ``dplan`` by fid
    task_specs: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    # root-drain bookkeeping: original root locations in drain order and
    # consumed token per location at the last journal write (adoption
    # re-pulls from the spool at token 0 — the token+attempt dedup
    # contract makes the re-pull idempotent; the tokens are recorded so
    # an operator can see how far the dead coordinator got)
    root_locations: List[str] = dataclasses.field(default_factory=list)
    root_tokens: Dict[str, int] = dataclasses.field(default_factory=dict)
    # terminal-result adoption: the query's root output copied into a
    # stable ``ha*`` spool stream at FINISH (outlives the query's own
    # spool GC), plus the client schema needed to serve it plan-free
    result_task_id: Optional[str] = None
    result_locations: int = 0
    result_bytes: int = 0
    column_names: List[str] = dataclasses.field(default_factory=list)
    column_types: List[str] = dataclasses.field(default_factory=list)
    row_count: int = 0
    # small results (utility statements, or spooling off) journal their
    # rows inline as the client-protocol JSON encoding
    inline_rows: Optional[List[list]] = None
    # cross-query result-cache adoption id (server/resultcache.py), when
    # this execution was admitted — a standby can re-serve repeats
    result_cache_task_id: Optional[str] = None
    # device-plane boundary checkpoints (parallel tier,
    # mesh_checkpoint_boundaries): fragment id (as str) ->
    # {task_id, n_out, rows, bytes}; the spooled pages live under
    # ``task_id`` with the query's own id prefix, so they are adopted
    # and GC'd exactly like HTTP task output.  A standby (or the
    # primary after a device fault) resumes the SPMD program from these
    # instead of re-running completed fragments
    device_checkpoints: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["placements"] = [list(p) for p in self.placements]
        return d

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "QueryJournal":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        kw["placements"] = [
            (int(f), str(t), str(u))
            for f, t, u in (d.get("placements") or [])]
        kw["attempts"] = {str(k): int(v)
                         for k, v in (d.get("attempts") or {}).items()}
        return cls(**kw)


class QueryStateStore:
    """The durable journal: one JSON object per query over the object
    API.  Writes are whole-object atomic (``LocalObjectApi.put`` is a
    tmp+rename publish), so a reader observes a consistent snapshot of
    one transition — never a torn doc."""

    def __init__(self, api: LocalObjectApi):
        self.api = api

    # -- journal ---------------------------------------------------------
    def write(self, journal: QueryJournal) -> None:
        self.api.put(_QUERY_PREFIX + journal.query_id,
                     json.dumps(journal.to_json()).encode("utf-8"))

    def read(self, query_id: str) -> Optional[QueryJournal]:
        try:
            data = self.api.get(_QUERY_PREFIX + query_id)
        except FileNotFoundError:
            return None
        return QueryJournal.from_json(json.loads(data))

    def list_queries(self) -> List[str]:
        return [k[len(_QUERY_PREFIX):]
                for k in self.api.list(_QUERY_PREFIX)]

    def delete(self, query_id: str) -> None:
        try:
            os.remove(self.api._path(_QUERY_PREFIX + query_id))
        except OSError:
            pass

    def gc_terminal(self, retention_s: float, max_entries: int,
                    now: Optional[float] = None) -> List[str]:
        """Journal GC: delete TERMINAL entries (FINISHED/FAILED) older
        than ``retention_s``, then — oldest first — any beyond
        ``max_entries`` terminal entries.  In-flight entries are never
        touched regardless of age: a standby must always be able to
        adopt them.  Returns the deleted query ids (sorted), for
        observability and tests."""
        now = time.time() if now is None else now
        terminal: List[Tuple[float, str]] = []
        for qid in self.list_queries():
            journal = self.read(qid)
            if journal is None or journal.state not in ("FINISHED",
                                                        "FAILED"):
                continue
            try:
                mtime = os.path.getmtime(
                    self.api._path(_QUERY_PREFIX + qid))
            except OSError:
                continue
            terminal.append((mtime, qid))
        terminal.sort()
        deleted = []
        for mtime, qid in terminal:
            if now - mtime > retention_s:
                self.delete(qid)
                deleted.append(qid)
        kept = [(m, q) for m, q in terminal if q not in deleted]
        if max_entries >= 0 and len(kept) > max_entries:
            for _, qid in kept[:len(kept) - max_entries]:
                self.delete(qid)
                deleted.append(qid)
        return sorted(deleted)

    # -- lease -----------------------------------------------------------
    def read_lease(self) -> Optional[Dict[str, Any]]:
        try:
            return json.loads(self.api.get(_LEASE_KEY))
        except FileNotFoundError:
            return None
        except ValueError:
            return None

    def _write_lease(self, owner: str, generation: int,
                     ttl_s: float) -> None:
        self.api.put(_LEASE_KEY, json.dumps({
            "owner": owner, "generation": generation,
            "expires_at": time.time() + ttl_s}).encode("utf-8"))

    def renew_lease(self, owner: str, generation: int,
                    ttl_s: float) -> bool:
        """Refresh the TTL; refuses when the lease moved to another
        owner/generation (this node was superseded and must stop
        acting as the coordinator)."""
        lease = self.read_lease()
        if lease is not None and (lease.get("owner") != owner
                                  or int(lease.get("generation", 0))
                                  != generation):
            return False
        self._write_lease(owner, generation, ttl_s)
        return True

    def try_claim_lease(self, owner: str, ttl_s: float,
                        force: bool = False) -> Optional[int]:
        """Compare-and-swap takeover: claim generation N+1 via an
        atomic create-if-absent marker.  Returns the won generation, or
        None (lease still live, or another claimant won the race).
        ``force`` skips the expiry check (primary startup on a fresh or
        crashed-over store)."""
        lease = self.read_lease()
        gen = int(lease.get("generation", 0)) if lease else 0
        if lease is not None and not force:
            if float(lease.get("expires_at", 0)) > time.time():
                return None          # still live: no takeover
        claim = f"{_CLAIM_PREFIX}{gen + 1:08d}"
        if not self.api.put_if_absent(claim, owner.encode("utf-8")):
            return None              # another claimant won this round
        self._write_lease(owner, gen + 1, ttl_s)
        return gen + 1


def make_state_store(config) -> Optional[QueryStateStore]:
    """Config-driven factory (``coordinator_state_path``); returns None
    when HA journaling is disabled — the default, which leaves every
    existing code path untouched."""
    root = getattr(config, "coordinator_state_path", "") or ""
    if not root:
        return None
    return QueryStateStore(LocalObjectApi(root))
