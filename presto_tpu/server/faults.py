"""Deterministic fault injection for the distributed tier.

The chaos substrate every distributed test reuses: an injector keyed by
(endpoint-pattern, method) with policies

    fail-n-times     first N matching requests fail (drop by default)
    http-503         answer 503 Service Unavailable
    drop-connection  close the socket without an HTTP response
    delay            hold the request for ``delay_s`` before serving

It hooks BOTH ends of a request:

- server side — the worker HTTP handler consults ``apply_server`` before
  dispatch and enacts the returned action;
- client side — ``RetryingHttpClient`` consults ``apply_client`` before
  issuing, so coordinator-originated requests can be failed without any
  server cooperation.

Everything is driven from tests; no rule means zero overhead beyond one
attribute check.  The injector records every injection so tests can
assert the fault actually fired.
"""

from __future__ import annotations

import re
import threading
import time
import urllib.error
from typing import Callable, List, Optional, Tuple

#: policy names (kept as strings so rules serialize trivially)
FAIL_N_TIMES = "fail-n-times"
HTTP_503 = "http-503"
DROP_CONNECTION = "drop-connection"
DELAY = "delay"
#: hold ONLY the task-results drain of a matching task until the test
#: releases the rule (or ``delay_s`` elapses) — the deterministic
#: straggler: the task executes normally, its consumers just cannot
#: pull its pages, which is exactly what speculation must beat
SLOW_TASK = "slow-task"
#: spool-store fault policies (server/spool.py reads consult
#: ``apply_spool``): a read raises an OSError for the first N matching
#: touches, a key is reported missing outright (FileNotFoundError), or
#: the read is delayed — the chaos shapes the retry-to-spool path must
#: survive (or fall back from, to PR 5 cascading retry)
SPOOL_READ_ERROR = "spool-read-error"
SPOOL_MISSING = "spool-missing"
#: device-plane fault policies (parallel checkpoint groups consult
#: ``apply_device`` before dispatching each group's SPMD program; keys
#: are ``{query_id}/f{fragment}/s{shard}``): a group fails with a
#: generic execution error, fails with an XLA-style RESOURCE_EXHAUSTED
#: message (the HBM-overflow shape), or is delayed before dispatch —
#: the mid-program chaos the boundary-checkpoint resume path must
#: survive with zero re-execution of checkpointed fragments
DEVICE_FAIL = "device-fail"
DEVICE_RESOURCE_EXHAUSTED = "device-resource-exhausted"
#: memory-plane fault policy (SqlTaskManager consults ``apply_memory``
#: at task create; keys are task ids ``{query_id}.{fragment}.{i}``): a
#: matching task reserves ``inflate_bytes`` extra for its lifetime — a
#: deterministic runaway query that fills the worker memory pool, which
#: is what the coordinator's low-memory killer must resolve
MEMORY_INFLATE = "memory-inflate"


class InjectedDeviceFault(RuntimeError):
    """Simulated device-plane execution failure (a mid-program loss of
    the collective data plane): distinguishable from query-semantic
    errors, so the coordinator's resume path engages."""


def kill_coordinator(coordinator) -> None:
    """Chaos: process-level coordinator death mid-query.  The
    coordinator's HTTP listeners stop, its takeover lease stops
    renewing, and every query thread halts with NO external side
    effects — no cancel fan-out, no spool GC, no events.  Worker tasks
    keep producing into the spool; the durable query-state journal
    (server/statestore.py) stays exactly as last written, which is what
    a standby coordinator adopts on takeover."""
    coordinator.kill()


class FaultRule:
    def __init__(self, pattern: str, method: str, policy: str, *,
                 times: Optional[int] = None, delay_s: float = 0.0,
                 status: int = 503, inflate_bytes: int = 0):
        if policy not in (FAIL_N_TIMES, HTTP_503, DROP_CONNECTION, DELAY,
                          SLOW_TASK, SPOOL_READ_ERROR, SPOOL_MISSING,
                          DEVICE_FAIL, DEVICE_RESOURCE_EXHAUSTED,
                          MEMORY_INFLATE):
            raise ValueError(f"unknown fault policy {policy!r}")
        self.pattern = pattern
        self.regex = re.compile(pattern)
        self.method = method.upper()
        self.policy = policy
        # fail-n-times defaults to 1 shot; other policies fire until
        # removed unless a count is given
        self.remaining = (times if times is not None
                          else (1 if policy == FAIL_N_TIMES else None))
        self.delay_s = delay_s
        self.status = status
        # memory-inflate: extra bytes a matching task reserves
        self.inflate_bytes = inflate_bytes
        # slow-task: requests block on this event rather than a timer,
        # so straggler tests are deterministic (release when ready);
        # ``delay_s`` > 0 doubles as a safety cap
        self.released = threading.Event()

    def release(self) -> None:
        """Unblock every request held by a slow-task rule."""
        self.released.set()

    def hold(self) -> None:
        # block on the event, not a sleep: deterministic release, with a
        # cap (delay_s when given, else 60s) so a forgotten release can
        # never hang CI
        self.released.wait(timeout=self.delay_s if self.delay_s > 0
                           else 60.0)

    def matches(self, path: str, method: str) -> bool:
        return (self.method in ("*", method.upper())
                and self.regex.search(path) is not None)

    def __repr__(self):
        return (f"FaultRule({self.pattern!r}, {self.method}, "
                f"{self.policy}, remaining={self.remaining})")


class InjectedFault(urllib.error.URLError):
    """Client-side simulated transport failure (classified retryable)."""

    def __init__(self, rule: FaultRule, url: str):
        super().__init__(ConnectionResetError(
            f"injected {rule.policy} on {url}"))
        self.rule = rule


class FaultInjector:
    def __init__(self, sleeper: Callable[[float], None] = time.sleep):
        self._lock = threading.Lock()
        self.rules: List[FaultRule] = []
        self.sleeper = sleeper
        #: (path, method, policy) per injection, for test assertions
        self.injections: List[Tuple[str, str, str]] = []

    def add_rule(self, pattern: str, method: str = "*",
                 policy: str = DROP_CONNECTION, *,
                 times: Optional[int] = None, delay_s: float = 0.0,
                 status: int = 503, inflate_bytes: int = 0) -> FaultRule:
        rule = FaultRule(pattern, method, policy, times=times,
                         delay_s=delay_s, status=status,
                         inflate_bytes=inflate_bytes)
        with self._lock:
            self.rules.append(rule)
        return rule

    def add_slow_task(self, task_pattern: str, *,
                      delay_s: float = 0.0) -> FaultRule:
        """Straggler policy: hold ONLY the task-results drain
        (``GET /v1/task/{id}/results/...``) of tasks matching
        ``task_pattern`` until ``rule.release()`` (or ``delay_s``).
        Task create, status polls, and every other endpoint stay fast —
        the task runs and finishes normally but its consumers starve,
        which is the shape speculative re-execution must beat."""
        return self.add_rule(
            rf"/v1/task/[^/]*{task_pattern}[^/]*/results/",
            method="GET", policy=SLOW_TASK, delay_s=delay_s)

    def add_spool_rule(self, pattern: str, policy: str = SPOOL_READ_ERROR,
                       *, times: Optional[int] = None,
                       delay_s: float = 0.0) -> FaultRule:
        """Spool-path chaos: ``pattern`` matches the spool key
        (``{task_id}/{partition}/{token}``), policy is one of
        spool-read-error (OSError, default 1 shot), spool-missing
        (FileNotFoundError until removed), or delay (slow read).  Spool
        rules are keyed method='SPOOL' so HTTP rules never leak onto
        the spool path and vice versa."""
        return self.add_rule(pattern, method="SPOOL", policy=policy,
                             times=times, delay_s=delay_s)

    def add_device_rule(self, pattern: str, policy: str = DEVICE_FAIL,
                        *, times: Optional[int] = None,
                        delay_s: float = 0.0) -> FaultRule:
        """Device-plane chaos: ``pattern`` matches the checkpoint-group
        dispatch key (``{query_id}/f{fragment_id}/s{shard}``), policy is
        one of device-fail (generic execution error, default 1 shot),
        device-resource-exhausted (the XLA HBM-overflow message shape),
        or delay (slow dispatch).  Failure policies default to ONE shot
        (a resume attempt must be able to get past the fault, exactly
        like fail-n-times); delay fires until removed.  Device rules
        are keyed method='DEVICE' so they never leak onto HTTP or spool
        paths."""
        if times is None and policy in (DEVICE_FAIL,
                                        DEVICE_RESOURCE_EXHAUSTED):
            times = 1
        return self.add_rule(pattern, method="DEVICE", policy=policy,
                             times=times, delay_s=delay_s)

    def add_memory_rule(self, pattern: str, inflate_bytes: int, *,
                        times: Optional[int] = None,
                        hold_s: float = 0.0) -> FaultRule:
        """Memory-plane chaos: a task whose id matches ``pattern``
        reserves ``inflate_bytes`` EXTRA for its lifetime (a real
        reservation through the task's memory-context tree, charging
        the node's pool) — the deterministic runaway query the
        low-memory killer must select and kill.  Defaults to ONE shot
        so exactly one victim inflates; memory rules are keyed
        method='MEMORY' and never leak onto HTTP/spool/device paths.

        ``hold_s`` > 0 makes the inflated task PARK after reserving —
        holding the pool memory until ``rule.release()``, the hold cap
        elapses, or the query is killed (pool abort) — so a runaway
        stays resident long enough for arbitration to act instead of
        finishing and freeing on its own."""
        if times is None:
            times = 1
        return self.add_rule(pattern, method="MEMORY",
                             policy=MEMORY_INFLATE, times=times,
                             inflate_bytes=inflate_bytes, delay_s=hold_s)

    def release_all(self) -> None:
        with self._lock:
            for rule in self.rules:
                rule.release()

    def clear(self) -> None:
        with self._lock:
            self.rules.clear()

    def _next_action(self, path: str, method: str
                     ) -> Optional[Tuple[FaultRule, str]]:
        with self._lock:
            for rule in self.rules:
                if not rule.matches(path, method):
                    continue
                if rule.remaining is not None:
                    if rule.remaining <= 0:
                        continue
                    rule.remaining -= 1
                self.injections.append((path, method, rule.policy))
                policy = (DROP_CONNECTION if rule.policy == FAIL_N_TIMES
                          else rule.policy)
                return rule, policy
        return None

    # -- client side ----------------------------------------------------
    def apply_client(self, url: str, method: str) -> None:
        """Raise the simulated failure (or delay) for a request the
        local node is about to issue."""
        hit = self._next_action(url, method)
        if hit is None:
            return
        rule, policy = hit
        if policy == DELAY:
            self.sleeper(rule.delay_s)
            return
        if policy == SLOW_TASK:
            rule.hold()
            return
        if policy == HTTP_503:
            import io

            raise urllib.error.HTTPError(
                url, rule.status, "injected fault", {},
                io.BytesIO(b'{"error": "injected fault"}'))
        raise InjectedFault(rule, url)

    # -- spool side -----------------------------------------------------
    def apply_spool(self, key: str) -> None:
        """Raise (or delay) for a spool-store read touching ``key``.
        Only method='SPOOL' rules apply here — never HTTP rules."""
        with self._lock:
            hit = None
            for rule in self.rules:
                if rule.method != "SPOOL" or \
                        rule.regex.search(key) is None:
                    continue
                if rule.remaining is not None:
                    if rule.remaining <= 0:
                        continue
                    rule.remaining -= 1
                self.injections.append((key, "SPOOL", rule.policy))
                hit = rule
                break
        if hit is None:
            return
        if hit.policy == DELAY:
            self.sleeper(hit.delay_s)
            return
        if hit.policy == SPOOL_MISSING:
            raise FileNotFoundError(f"injected spool-missing on {key}")
        raise OSError(f"injected spool read error on {key}")

    # -- device side ----------------------------------------------------
    def apply_device(self, key: str) -> None:
        """Raise (or delay) for a checkpoint-group dispatch touching
        ``key``.  Only method='DEVICE' rules apply here."""
        with self._lock:
            hit = None
            for rule in self.rules:
                if rule.method != "DEVICE" or \
                        rule.regex.search(key) is None:
                    continue
                if rule.remaining is not None:
                    if rule.remaining <= 0:
                        continue
                    rule.remaining -= 1
                self.injections.append((key, "DEVICE", rule.policy))
                hit = rule
                break
        if hit is None:
            return
        if hit.policy == DELAY:
            self.sleeper(hit.delay_s)
            return
        if hit.policy == DEVICE_RESOURCE_EXHAUSTED:
            raise InjectedDeviceFault(
                f"RESOURCE_EXHAUSTED: injected device OOM at {key}")
        raise InjectedDeviceFault(f"injected device failure at {key}")

    # -- memory side ----------------------------------------------------
    def apply_memory(self, task_id: str
                     ) -> Tuple[int, Optional[FaultRule]]:
        """(bytes, rule) of injected reservation for a task being
        created ((0, None) = no inflation).  The rule rides along so
        the task can honor a ``hold_s`` park and the test can
        ``release()`` it.  Only method='MEMORY' rules apply here."""
        with self._lock:
            for rule in self.rules:
                if rule.method != "MEMORY" or \
                        rule.regex.search(task_id) is None:
                    continue
                if rule.remaining is not None:
                    if rule.remaining <= 0:
                        continue
                    rule.remaining -= 1
                self.injections.append((task_id, "MEMORY", rule.policy))
                return rule.inflate_bytes, rule
        return 0, None

    # -- server side ----------------------------------------------------
    def apply_server(self, path: str, method: str
                     ) -> Optional[Tuple[str, FaultRule]]:
        """Returns None (serve normally) or (policy, rule) for the
        handler to enact: 'http-503' | 'drop-connection'; 'delay' is
        applied here and then served normally."""
        hit = self._next_action(path, method)
        if hit is None:
            return None
        rule, policy = hit
        if policy == DELAY:
            self.sleeper(rule.delay_s)
            return None
        if policy == SLOW_TASK:
            rule.hold()
            return None
        return policy, rule
