"""Authenticating forward proxy for the statement protocol.

The presto-proxy role (1,243 LoC: an HTTP proxy that authenticates
clients, stamps the verified principal, forwards statement requests to
the real coordinator, and rewrites ``nextUri`` so clients keep talking
to the proxy).  Same shape here over the stdlib HTTP server.

Reference: presto-proxy/src/main/java/io/prestosql/proxy/
ProxyResource.java (forward + URI rewrite), ProxyServlet.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class ProxyServer:
    def __init__(self, coordinator_uri: str, authenticator=None,
                 port: int = 0, internal_secret: Optional[str] = None):
        from presto_tpu.server.security import InternalAuthenticator

        self.coordinator_uri = coordinator_uri.rstrip("/")
        self.authenticator = authenticator
        # the proxy is a trusted peer: it authenticates the client and
        # identifies itself to the coordinator with the cluster token,
        # vouching for the X-Presto-User it stamps
        self.internal_auth = (InternalAuthenticator(internal_secret)
                              if internal_secret else None)
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, code: int, body: bytes,
                       content_type: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _auth(self) -> Optional[str]:
                """Authenticated user, or None after sending 401."""
                if proxy.authenticator is None:
                    return self.headers.get("X-Presto-User", "user")
                user = proxy.authenticator.authenticate_basic(
                    self.headers.get("Authorization"))
                if user is None:
                    # drain any body first: leaving it unread desyncs
                    # HTTP/1.1 keep-alive framing; also end the
                    # connection so the client restarts cleanly
                    n = int(self.headers.get("Content-Length", 0))
                    if n:
                        self.rfile.read(n)
                    self.send_response(401)
                    self.send_header("WWW-Authenticate",
                                     'Basic realm="presto-tpu-proxy"')
                    self.send_header("Content-Length", "0")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.close_connection = True
                    return None
                return user

            def _forward(self, method: str, user: str,
                         body: Optional[bytes] = None) -> None:
                url = proxy.coordinator_uri + self.path
                headers = {"X-Presto-User": user,
                           "Content-Type": "text/plain"}
                # client-session state must survive the hop (session
                # properties, catalog, prepared statements)
                for h in ("X-Presto-Session", "X-Presto-Catalog",
                          "X-Presto-Schema",
                          "X-Presto-Prepared-Statements"):
                    v = self.headers.get(h)
                    if v:
                        headers[h] = v
                if proxy.internal_auth is not None:
                    headers.update(proxy.internal_auth.header())
                req = urllib.request.Request(
                    url, data=body, method=method, headers=headers)
                try:
                    with urllib.request.urlopen(req, timeout=120) as resp:
                        payload = resp.read()
                        code = resp.status
                except urllib.error.HTTPError as e:
                    payload, code = e.read(), e.code
                # clients must keep talking to the proxy: rewrite every
                # coordinator URI in the payload (nextUri etc.)
                payload = payload.replace(
                    proxy.coordinator_uri.encode(), proxy.uri.encode())
                self._reply(code, payload)

            def do_POST(self):  # noqa: N802
                user = self._auth()
                if user is None:
                    return
                n = int(self.headers.get("Content-Length", 0))
                self._forward("POST", user, self.rfile.read(n))

            def do_GET(self):  # noqa: N802
                user = self._auth()
                if user is None:
                    return
                self._forward("GET", user)

            def do_DELETE(self):  # noqa: N802
                # query cancel rides the same rewritten URIs
                user = self._auth()
                if user is None:
                    return
                self._forward("DELETE", user)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self.uri = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="proxy-http")
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
