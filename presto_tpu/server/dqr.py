"""DistributedQueryRunner: a real multi-node cluster in one process.

The reference's key test trick (presto-testing/.../DistributedQueryRunner
.java:73,97-123): boot a real coordinator and N-1 workers in one JVM with
real HTTP on ephemeral ports and real exchanges, giving multi-node
behavior without a cluster.  Identical here: one CoordinatorServer + N
WorkerServers on 127.0.0.1 ephemeral ports, workers announced to the
coordinator's discovery, queries executed through the real client
protocol with real serde'd pages on the exchange wire.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from presto_tpu import types as T
from presto_tpu.client import StatementClient
from presto_tpu.config import DEFAULT, EngineConfig
from presto_tpu.connectors.api import ConnectorRegistry
from presto_tpu.localrunner import QueryResult
from presto_tpu.server.coordinator import CoordinatorServer
from presto_tpu.server.worker import WorkerServer


class DistributedQueryRunner:
    def __init__(self, registry_factory: Callable[[], ConnectorRegistry],
                 default_catalog: str, n_workers: int = 3,
                 config: EngineConfig = DEFAULT, verbose: bool = False,
                 internal_secret: Optional[str] = None,
                 coordinator_injector=None, worker_injectors=None,
                 heartbeat_interval_s: float = 0.5,
                 heartbeat_max_missed: int = 3,
                 event_log_path: Optional[str] = None,
                 resource_groups=None):
        # each node builds its own registry, as each reference node loads
        # its own connector instances from catalog config
        # ``coordinator_injector`` fails coordinator-originated requests
        # client-side; ``worker_injectors`` (index -> FaultInjector) hook
        # each worker's HTTP handler (server/faults.py chaos substrate)
        self.internal_secret = internal_secret
        self.coordinator = CoordinatorServer(
            registry_factory(), default_catalog, config, verbose=verbose,
            internal_secret=internal_secret,
            fault_injector=coordinator_injector,
            heartbeat_interval_s=heartbeat_interval_s,
            heartbeat_max_missed=heartbeat_max_missed,
            event_log_path=event_log_path,
            resource_groups=resource_groups)
        # the coordinator's event stream (EventListener SPI): register
        # listeners here to observe query/retry/speculation events
        self.event_bus = self.coordinator.event_bus

        def cluster_registry() -> ConnectorRegistry:
            # system.runtime.* backed by live coordinator state, fetched
            # over HTTP at scan time (the GlobalSystemConnector role)
            reg = registry_factory()
            from presto_tpu.connectors.system import SystemConnector

            co_uri = self.coordinator.uri

            def fetch(path):
                import json
                import urllib.request

                with urllib.request.urlopen(f"{co_uri}{path}",
                                            timeout=10) as resp:
                    return json.loads(resp.read())

            def nodes_fn():
                info = fetch("/v1/info")
                return [(nid, uri, "dev", False, "ACTIVE")
                        for nid, uri in info.get("nodes", [])]

            def queries_fn():
                # fed live from the coordinator's stats rollup
                return [(q["queryId"], q["state"], q.get("user"),
                         q["query"], q.get("outputRows", 0),
                         q.get("wallS", 0.0),
                         q.get("peakMemoryBytes", 0),
                         q.get("stageRetryRounds", 0),
                         q.get("recoveryRounds", 0),
                         q.get("traceToken"),
                         q.get("spooledPages", 0),
                         q.get("producerReruns", 0),
                         q.get("queuedS", 0.0),
                         q.get("resourceGroup"),
                         q.get("planCached", False),
                         q.get("completedSplits", 0),
                         q.get("totalSplits", 0),
                         q.get("progressPercent", 0.0),
                         q.get("resultCached", False),
                         q.get("resultCacheBytes", 0),
                         q.get("errorName"))
                        for q in fetch("/v1/query")]

            def tasks_fn():
                out = []
                for t in fetch("/v1/tasks"):
                    ts = t.get("taskStats") or {}
                    out.append((t["taskId"], t["state"],
                                t["taskId"].rsplit(".", 2)[0],
                                ts.get("output_rows", 0),
                                round(ts.get("wall_ns", 0) / 1e6, 3),
                                ts.get("peak_memory_bytes", 0),
                                round(ts.get("elapsed_s", 0.0), 6)))
                return out

            reg.register("system", SystemConnector(
                nodes_fn=nodes_fn, queries_fn=queries_fn,
                tasks_fn=tasks_fn))
            return reg

        # the coordinator needs the system schemas for planning (data is
        # served by worker-side scans)
        from presto_tpu.connectors.system import SystemConnector

        self.coordinator.registry.register("system", SystemConnector())

        self.workers: List[WorkerServer] = []
        for i in range(n_workers):
            # two simulated racks: placement spreads tasks across them
            w = WorkerServer(cluster_registry(), config,
                             node_id=f"worker-{i}",
                             internal_secret=internal_secret,
                             location=f"rack{i % 2}",
                             fault_injector=(worker_injectors or
                                             {}).get(i))
            self.workers.append(w)
            self._announce(w)
        self.client = StatementClient(self.coordinator.uri)

    def new_client(self, user: Optional[str] = None) -> StatementClient:
        """A fresh StatementClient against this cluster's coordinator.
        StatementClient carries per-connection session state, so every
        concurrent load-generator thread needs its own (the serving-tier
        qps harness / tests/test_serving.py)."""
        return StatementClient(self.coordinator.uri, user=user)

    def kill_worker(self, i: int) -> WorkerServer:
        """Abruptly stop worker ``i`` (chaos: simulated node death — the
        coordinator learns of it only through missed heartbeats)."""
        w = self.workers.pop(i)
        w.close()
        return w

    def _announce(self, worker: WorkerServer,
                  coordinator_uri: Optional[str] = None) -> None:
        import json
        import urllib.request

        body = json.dumps({"nodeId": worker.node_id,
                           "uri": worker.uri,
                           "location": worker.location,
                           "meshFingerprint":
                               worker.mesh_fingerprint}).encode()
        headers = {"Content-Type": "application/json"}
        if self.internal_secret:
            from presto_tpu.server.security import InternalAuthenticator

            headers.update(
                InternalAuthenticator(self.internal_secret).header())
        req = urllib.request.Request(
            f"{coordinator_uri or self.coordinator.uri}/v1/announcement",
            data=body, method="POST", headers=headers)
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200

    @classmethod
    def tpch(cls, scale: float = 0.01, n_workers: int = 3,
             config: EngineConfig = DEFAULT,
             **kwargs) -> "DistributedQueryRunner":
        from presto_tpu.connectors.memory import MemoryConnector

        # One shared memory connector instance across every in-process
        # node: coordinator-side DDL/DML lands in storage that worker
        # scans see — the same effective topology as the reference's
        # presto-memory, whose per-node stores are fed by distributed
        # writes (here writes run coordinator-side).
        shared_memory = MemoryConnector()

        def factory() -> ConnectorRegistry:
            from presto_tpu.connectors.tpch import TpchConnector

            reg = ConnectorRegistry()
            reg.register("tpch", TpchConnector(scale=scale))
            reg.register("memory", shared_memory)
            return reg

        return cls(factory, "tpch", n_workers, config, **kwargs)

    @classmethod
    def tpcds(cls, scale: float = 0.003, n_workers: int = 2,
              config: EngineConfig = DEFAULT,
              **kwargs) -> "DistributedQueryRunner":
        """TPC-DS on the HTTP mesh — the BASELINE.md multi-chip configs
        (Q72/Q95) run through real coordinator + workers + exchanges;
        the chaos tier drives this cluster under the fault injector."""
        from presto_tpu.connectors.memory import MemoryConnector

        shared_memory = MemoryConnector()

        def factory() -> ConnectorRegistry:
            from presto_tpu.connectors.tpcds import TpcdsConnector
            from presto_tpu.connectors.tpch import TpchConnector

            reg = ConnectorRegistry()
            reg.register("tpcds", TpcdsConnector(scale=scale))
            reg.register("tpch", TpchConnector(scale=scale))
            reg.register("memory", shared_memory)
            return reg

        return cls(factory, "tpcds", n_workers, config, **kwargs)

    def execute(self, sql: str) -> QueryResult:
        columns, data = self.client.execute(sql)
        names = [c["name"] for c in columns]
        types = [T.parse_type(c["type"]) for c in columns]
        rows = [tuple(_from_json(v, typ) for v, typ in zip(row, types))
                for row in data]
        return QueryResult(names, types, rows)

    def close(self) -> None:
        for w in self.workers:
            w.close()
        self.coordinator.close()

    def __enter__(self) -> "DistributedQueryRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class HAQueryRunner(DistributedQueryRunner):
    """A DistributedQueryRunner plus a STANDBY coordinator sharing the
    same durable state (spool + query-state journal): the coordinator-HA
    test/chaos harness.  The standby watches the takeover lease; when
    the primary is killed (``kill_primary`` — the faults.py
    ``kill_coordinator`` process-death shape), the standby claims the
    lease, adopts the journal, and serves every in-flight query.
    Workers are stateless announcers re-announcing to BOTH coordinators
    on a cadence, and ``client`` follows failover across the address
    list.  Requires ``config.coordinator_state_path`` to be set."""

    def __init__(self, registry_factory, default_catalog: str,
                 n_workers: int = 2, config: EngineConfig = DEFAULT,
                 **kwargs):
        if not config.coordinator_state_path:
            raise ValueError("HAQueryRunner needs "
                             "config.coordinator_state_path")
        super().__init__(registry_factory, default_catalog, n_workers,
                         config, **kwargs)
        from presto_tpu.connectors.system import SystemConnector

        self.standby = CoordinatorServer(
            registry_factory(), default_catalog, config,
            standby_of=self.coordinator.uri,
            internal_secret=self.internal_secret,
            heartbeat_interval_s=kwargs.get("heartbeat_interval_s", 0.5),
            heartbeat_max_missed=kwargs.get("heartbeat_max_missed", 3),
            event_log_path=kwargs.get("event_log_path"))
        self.standby.registry.register("system", SystemConnector())
        # stateless announcers: every worker re-announces to both
        # coordinators on a cadence, so the standby knows the live
        # cluster the moment it takes over
        import threading

        for w in self.workers:
            w.announce_to = [self.coordinator.uri, self.standby.uri]
            self._announce(w, self.standby.uri)
            threading.Thread(
                target=w._announce_loop, args=(0.5,), daemon=True,
                name=f"announce-{w.node_id}").start()
        self.client = StatementClient(
            self.coordinator.uri, standby_uris=[self.standby.uri])

    def new_client(self, user: Optional[str] = None) -> StatementClient:
        return StatementClient(self.coordinator.uri, user=user,
                               standby_uris=[self.standby.uri])

    def kill_primary(self) -> None:
        """Process-level death of the active coordinator mid-query
        (server/faults.py ``kill_coordinator``)."""
        from presto_tpu.server.faults import kill_coordinator

        kill_coordinator(self.coordinator)

    def wait_for_failover(self, timeout_s: float = 30.0) -> None:
        """Block until the standby won the lease and is active."""
        import time as _t

        deadline = _t.monotonic() + timeout_s
        while _t.monotonic() < deadline:
            if self.standby.is_active:
                return
            _t.sleep(0.02)
        raise TimeoutError("standby never became active")

    def close(self) -> None:
        super().close()
        self.standby.close()


def _from_json(v, typ: T.Type):
    """Invert the client protocol's JSON value encoding."""
    import datetime

    if v is None:
        return None
    if typ.name == "date" and isinstance(v, str):
        return datetime.date.fromisoformat(v)
    if typ.name == "timestamp" and isinstance(v, str):
        return datetime.datetime.fromisoformat(v)
    return v
